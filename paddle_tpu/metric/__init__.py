"""Metrics (reference surface: python/paddle/metric/metrics.py —
Accuracy/Precision/Recall/Auc with update/accumulate/reset)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from .. import ops


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = np.asarray(pred._array if isinstance(pred, Tensor) else pred)
        label_np = np.asarray(label._array if isinstance(label, Tensor) else label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = topk_idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(correct._array if isinstance(correct, Tensor) else correct)
        n = c.shape[0] if c.ndim else 1
        accs = []
        for i, k in enumerate(self.topk):
            num = float(c[..., :k].sum())
            self.total[i] += num
            self.count[i] += n
            accs.append(num / max(n, 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds._array if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._array if isinstance(labels, Tensor) else labels)
        pred_pos = (p > 0.5).astype(np.int64).reshape(-1)
        l = l.reshape(-1)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fp += int(((pred_pos == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds._array if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._array if isinstance(labels, Tensor) else labels)
        pred_pos = (p > 0.5).astype(np.int64).reshape(-1)
        l = l.reshape(-1)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fn += int(((pred_pos == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name or "auc"
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds._array if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._array if isinstance(labels, Tensor) else labels).reshape(-1)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        idx = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoidal over thresholds, descending
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    """Functional accuracy (reference: paddle.metric.accuracy)."""
    pred = np.asarray(input._array if isinstance(input, Tensor) else input)
    lab = np.asarray(label._array if isinstance(label, Tensor) else label)
    if lab.ndim == 2 and lab.shape[1] == 1:
        lab = lab[:, 0]
    topk = np.argsort(-pred, axis=-1)[:, :k]
    correct = (topk == lab[:, None]).any(axis=1)
    return Tensor(np.asarray(correct.mean(), np.float32))
