"""paddle.hub — hubconf-protocol model loading (reference:
python/paddle/hapi/hub.py list:170 / help:214 / load:256).

``source='local'`` is fully supported: a repo directory containing
``hubconf.py`` whose public callables are the entrypoints (the reference's
``dependencies`` variable is honoured).  ``github``/``gitee`` sources
require network egress, which this build does not have — they raise a
curated error instead of silently hanging."""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

MODULE_HUBCONF = "hubconf.py"
VAR_DEPENDENCY = "dependencies"

_builtin_list = list  # shadowed by the API name below


def _no_network(source):
    raise RuntimeError(
        "paddle.hub source=%r requires network access, which this build "
        "does not have (zero-egress TPU environment). Clone the repository "
        "locally and call with source='local'." % (source,))


def _import_hubconf(repo_dir):
    repo_dir = os.path.expanduser(repo_dir)
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError("Cannot find %s in %r" % (MODULE_HUBCONF,
                                                          repo_dir))
    sys.path.insert(0, repo_dir)
    try:
        spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf",
                                                      path)
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
    finally:
        sys.path.remove(repo_dir)
    deps = getattr(m, VAR_DEPENDENCY, None)
    if deps:
        missing = []
        for pkg in deps:
            try:
                __import__(pkg)
            except ImportError:
                missing.append(pkg)
        if missing:
            raise RuntimeError("Missing dependencies: %s"
                               % ", ".join(missing))
    return m


def _check_source(source):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            'Unknown source: "%s". Allowed values: "github" | "gitee" | '
            '"local".' % (source,))
    if source in ("github", "gitee"):
        _no_network(source)


def list(repo_dir, source="local", force_reload=False):
    """List entrypoint names exported by the repo's hubconf.py."""
    _check_source(source)
    m = _import_hubconf(repo_dir)
    return [f for f in dir(m)
            if callable(getattr(m, f)) and not f.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    """Return the docstring of one entrypoint."""
    _check_source(source)
    m = _import_hubconf(repo_dir)
    fn = getattr(m, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError("Cannot find callable %s in hubconf" % (model,))
    return fn.__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Call entrypoint ``model`` from the repo's hubconf.py."""
    _check_source(source)
    m = _import_hubconf(repo_dir)
    fn = getattr(m, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError("Cannot find callable %s in hubconf" % (model,))
    return fn(**kwargs)
