"""Global PRNG management.

TPU-native rethink of the reference's generator registry
(reference: paddle/fluid/framework/generator.cc, python/paddle/framework/random.py):
instead of stateful per-device Philox generators, a root ``jax.random`` key
plus a monotonically increasing fold-in counter.  Layers that need randomness
(dropout, random init) draw fresh keys from the default generator; compiled
step functions instead thread an explicit key (see paddle_tpu.jit) through a
scoped override so traces stay functional.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax
import jax.numpy as jnp


class Generator:
    """A stream of PRNG keys derived from one root seed."""

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._key = jax.random.key(self._seed)
        self._counter = 0

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.key(self._seed)
        self._counter = 0
        return self

    def seed(self, seed: int):
        return self.manual_seed(seed)

    @property
    def initial_seed(self):
        return self._seed

    def next_key(self):
        self._counter += 1
        return jax.random.fold_in(self._key, self._counter)

    def split(self, n: int):
        return jax.random.split(self.next_key(), n)

    def get_state(self):
        return {"seed": self._seed, "counter": self._counter}

    def set_state(self, state):
        self._seed = int(state["seed"])
        self._key = jax.random.key(self._seed)
        self._counter = int(state["counter"])


_default_generator = Generator(0)

# When a compiled trace supplies an explicit key stream, it is pushed here so
# layer-level randomness (dropout) becomes a pure function of that key.
_key_stream_stack = []


def default_generator() -> Generator:
    return _default_generator


def seed(s: int):
    """paddle.seed equivalent — reseed the global generator."""
    _default_generator.manual_seed(s)
    return _default_generator


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


class _KeyStream:
    """Functional key stream: fold_in over an explicit base key.

    Safe under jit tracing — the fold-in counter advances at trace time, so
    every dropout site in a traced step gets a distinct, deterministic subkey
    of the step's key argument.
    """

    def __init__(self, base_key):
        self.base_key = base_key
        self._counter = 0

    def next_key(self):
        self._counter += 1
        return jax.random.fold_in(self.base_key, self._counter)


@contextlib.contextmanager
def key_stream(base_key):
    """Scope in which layer randomness draws from ``base_key``."""
    stream = _KeyStream(base_key)
    _key_stream_stack.append(stream)
    try:
        yield stream
    finally:
        _key_stream_stack.pop()


def next_key():
    """Fresh PRNG key: from the innermost explicit stream if any, else the
    global eager generator."""
    if _key_stream_stack:
        return _key_stream_stack[-1].next_key()
    return _default_generator.next_key()
