"""The eager Tensor.

Design (TPU-native rethink of the reference's eager Tensor):

* A ``Tensor`` is a thin wrapper around a ``jax.Array`` (or a jax tracer while
  inside a ``jit`` trace).  All math routes through ``jax.numpy`` so the same
  op code serves the eager path and the compiled (``to_static``/``pjit``) path.
* Autograd is a dynamic graph of ``GradNode`` objects built per-op via
  ``jax.vjp`` closures — the structural analogue of the reference's eager
  autograd (reference: paddle/fluid/eager/grad_node_info.h:90 GradNodeBase,
  autograd_meta.h AutogradMeta), with ``jax.vjp`` replacing generated grad
  kernels.
* ``stop_gradient`` defaults to True for plain tensors and False for
  ``Parameter``s, matching reference semantics
  (reference: python/paddle/fluid/framework.py Parameter).

The fast training path never walks this tape: ``paddle_tpu.jit.to_static`` /
``TrainStep`` trace the same ops under ``jax.grad`` where the tape is disabled.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as _dtype_mod
from .grad_mode import is_grad_enabled, no_grad

Array = Any


class GradNode:
    """One recorded op in the autograd graph.

    Holds the ``jax.vjp`` pullback for the op, strong references to the input
    tensors (the analogue of the reference's TensorWrapper saved-tensors,
    reference: paddle/fluid/eager/tensor_wrapper.h) and the output avals so
    missing cotangents can be zero-filled.
    """

    __slots__ = ("vjp_fn", "inputs", "out_avals", "name", "out_treedef")

    def __init__(self, vjp_fn, inputs, out_avals, name, out_treedef=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs          # list[Tensor] — differentiable inputs, in vjp order
        self.out_avals = out_avals    # list[(shape, dtype)] per output position
        self.name = name
        self.out_treedef = out_treedef

    def __repr__(self):
        return f"<GradNode {self.name} n_in={len(self.inputs)} n_out={len(self.out_avals)}>"


def _to_array(data, dtype=None):
    if isinstance(data, Tensor):
        arr = data._array
        if dtype is not None:
            arr = arr.astype(dtype)
        return arr
    if isinstance(data, (jnp.ndarray, jax.Array)) or hasattr(data, "aval"):
        return data if dtype is None else data.astype(dtype)
    if isinstance(data, np.ndarray):
        if dtype is None and data.dtype == np.float64:
            dtype = _dtype_mod.get_default_dtype()
        return jnp.asarray(data, dtype=dtype)
    if isinstance(data, (bool, int, float, complex)):
        if dtype is None:
            if isinstance(data, bool):
                dtype = np.dtype("bool")
            elif isinstance(data, int):
                dtype = np.dtype("int64")
            elif isinstance(data, float):
                dtype = _dtype_mod.get_default_dtype()
            else:
                dtype = np.dtype("complex64")
        return jnp.asarray(data, dtype=dtype)
    if isinstance(data, (list, tuple)):
        arr = np.asarray(data)
        if dtype is None and arr.dtype == np.float64:
            dtype = _dtype_mod.get_default_dtype()
        return jnp.asarray(arr, dtype=dtype)
    return jnp.asarray(data, dtype=dtype)


class Tensor:
    __slots__ = ("_array", "_stop_gradient", "_grad_node", "_out_index",
                 "grad", "name", "_backward_hooks", "persistable", "__weakref__")

    # let Tensor win against numpy array in mixed binary ops
    __array_priority__ = 100

    def __init__(self, data, dtype=None, stop_gradient=True, name=None):
        dtype = _dtype_mod.convert_dtype(dtype)
        self._array = _to_array(data, dtype)
        self._stop_gradient = bool(stop_gradient)
        self._grad_node: Optional[GradNode] = None
        self._out_index = 0
        self.grad: Optional[Tensor] = None
        self.name = name
        self._backward_hooks = None
        self.persistable = False

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._array.shape)

    @property
    def ndim(self):
        return self._array.ndim

    # paddle alias
    @property
    def dim(self):
        return self._array.ndim

    @property
    def size(self):
        return int(np.prod(self._array.shape)) if self._array.shape else 1

    @property
    def dtype(self):
        return np.dtype(self._array.dtype)

    @property
    def T(self):
        from .. import ops
        return ops.t(self)

    @property
    def mT(self):
        from .. import ops
        return ops.matrix_transpose(self)

    @property
    def stop_gradient(self):
        return self._stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, value):
        self._stop_gradient = bool(value)

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def place(self):
        devs = getattr(self._array, "devices", None)
        if devs is None:
            return "traced"
        try:
            return str(next(iter(self._array.devices())))
        except Exception:
            return "traced"

    def numpy(self):
        return np.asarray(self._array)

    def item(self, *args):
        return self.numpy().item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._array.shape[0]

    def __iter__(self):
        # explicit __iter__ is REQUIRED: without it Python falls back to
        # the __getitem__ protocol with ever-growing indices, and jax's
        # clamping gather never raises IndexError -> infinite loop on any
        # eager `for row in tensor` (reference tensors iterate rows)
        if self.ndim == 0:
            raise TypeError("iteration over a 0-d tensor")
        return (self[i] for i in range(self._array.shape[0]))

    def __bool__(self):
        return bool(self._array)

    def __int__(self):
        return int(self._array)

    def __float__(self):
        return float(self._array)

    def __index__(self):
        return int(self._array)

    def __hash__(self):
        return id(self)

    def __repr__(self):
        grad_part = "" if self._stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_part},\n       {np.asarray(self._array) if not self._is_traced() else self._array!r})")

    def _is_traced(self):
        return not isinstance(self._array, (np.ndarray,)) and not hasattr(self._array, "devices")

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        """Run reverse accumulation from this tensor.

        Reference analogue: egr::Backward (paddle/fluid/eager/backward.cc:797).
        """
        from .engine import run_backward
        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._array))
        else:
            self.grad = None

    def register_hook(self, hook):
        """Register a gradient hook; returns a removable handle.

        Reference analogue: egr::utils RegisterGradientHookForTensor /
        VarBase._register_grad_hook.
        """
        if self._backward_hooks is None:
            self._backward_hooks = {}
        hid = len(self._backward_hooks)
        self._backward_hooks[hid] = hook
        tensor = self

        class _Handle:
            def remove(self):
                tensor._backward_hooks.pop(hid, None)

        return _Handle()

    def detach(self):
        t = Tensor(self._array, stop_gradient=True)
        t.name = self.name
        return t

    def detach_(self):
        self._grad_node = None
        self._stop_gradient = True
        return self

    def clone(self):
        from .. import ops
        return ops.assign(self)

    # -- mutation (leaf-only, used by optimizers / state loading) -----------
    def set_value(self, value):
        arr = _to_array(value)
        if tuple(arr.shape) != tuple(self._array.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self._array.shape}")
        self._array = arr.astype(self._array.dtype)
        return self

    def copy_(self, other):
        return self.set_value(other)

    def _replace_array(self, arr):
        """Internal: swap the underlying buffer (optimizer fast path)."""
        self._array = arr
        return self

    def astype(self, dtype):
        from .. import ops
        return ops.cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    def to(self, *args, **kwargs):
        # minimal: dtype and/or device
        dtype = kwargs.get("dtype")
        device = kwargs.get("device")
        for a in args:
            if isinstance(a, str) and (a in _dtype_mod._ALIASES or "int" in a or "float" in a or "bool" in a):
                dtype = a
            else:
                device = a
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            arr = jax.device_put(out._array, device if not isinstance(device, str) else _resolve_device(device))
            out = Tensor(arr, stop_gradient=out.stop_gradient)
        return out

    def cpu(self):
        return Tensor(np.asarray(self._array), stop_gradient=self._stop_gradient)

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):  # API-compat: "cuda" == accelerator
        return self

    # elementwise/methods are attached by paddle_tpu.ops.methods at import time


class Parameter(Tensor):
    """A trainable tensor (reference: python/paddle/fluid/framework.py Parameter)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed", "pspec")

    _param_counter = [0]

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        if name is None:
            Parameter._param_counter[0] += 1
            self.name = f"param_{Parameter._param_counter[0]}"
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.pspec = None  # optional jax PartitionSpec annotation
        self.persistable = True

    @property
    def trainable_(self):
        return self.trainable

    def __repr__(self):
        return "Parameter " + super().__repr__()


def _resolve_device(name: str):
    name = name.lower()
    if name in ("cpu",):
        return jax.devices("cpu")[0]
    if name in ("gpu", "cuda", "tpu", "accelerator", "xla"):
        return jax.devices()[0]
    if ":" in name:
        kind, idx = name.split(":")
        return jax.devices(kind if kind not in ("gpu", "cuda") else None)[int(idx)]
    return jax.devices()[0]


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor equivalent."""
    t = Tensor(data, dtype=dtype, stop_gradient=stop_gradient)
    if place is not None:
        t = t.to(place)
        t.stop_gradient = stop_gradient
    return t
