from . import dtype
from .dispatch import call, unwrap, wrap_op
from .engine import grad, run_backward
from .grad_mode import enable_grad, is_grad_enabled, no_grad, set_grad_enabled
from .random import (Generator, default_generator, get_rng_state, key_stream,
                     next_key, seed, set_rng_state)
from .tensor import Parameter, Tensor, is_tensor, to_tensor

__all__ = [
    "Tensor", "Parameter", "to_tensor", "is_tensor",
    "no_grad", "enable_grad", "set_grad_enabled", "is_grad_enabled",
    "grad", "run_backward", "call", "wrap_op", "unwrap",
    "seed", "Generator", "default_generator", "next_key", "key_stream",
    "get_rng_state", "set_rng_state", "dtype",
]
