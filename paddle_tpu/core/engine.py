"""The eager backward engine.

Topology-ordered reverse traversal of the GradNode graph with fan-in
accumulation — the structural analogue of the reference's
egr::RunBackward (paddle/fluid/eager/backward.cc:522): a dependency-counted
queue over grad nodes, a GradTensorHolder per node for cotangent
accumulation, and leaf accumulation writing ``.grad``.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp

from .grad_mode import no_grad
from .tensor import GradNode, Tensor


def _ones_like(arr):
    return jnp.ones(arr.shape, arr.dtype)


def _collect_graph(roots: List[GradNode]):
    """Reachable nodes + per-node consumer-edge counts.

    pending[n] = number of cotangent contributions node ``n`` will receive
    from reachable consumer nodes before its vjp can run
    (reference analogue: node_in_degree_map, backward.cc:449-483).
    """
    pending: Dict[int, int] = {}
    nodes: Dict[int, GradNode] = {}
    stack = list(roots)
    seen = set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        nodes[id(node)] = node
        for t in node.inputs:
            prod = t._grad_node
            if prod is not None:
                pending[id(prod)] = pending.get(id(prod), 0) + 1
                if id(prod) not in seen:
                    stack.append(prod)
    return nodes, pending


def run_backward(tensors: List[Tensor], grad_tensors: List[Optional[Tensor]],
                 retain_graph: bool = False,
                 inputs: Optional[List[Tensor]] = None,
                 accumulate_into_grad: bool = True):
    """Core engine. If ``inputs`` given, also return their gradients
    (paddle.grad path); otherwise write ``.grad`` on leaves."""
    with no_grad():
        return _run(tensors, grad_tensors, retain_graph, inputs,
                    accumulate_into_grad)


def _run(tensors, grad_tensors, retain_graph, inputs, accumulate_into_grad):
    # node-id -> list of accumulated cotangents per output position
    buffers: Dict[int, list] = {}
    # id(tensor) -> accumulated grad array (leaf accumulation)
    leaf_grads: Dict[int, object] = {}
    leaf_tensors: Dict[int, Tensor] = {}

    roots = []
    for t, g in zip(tensors, grad_tensors):
        if t._grad_node is None:
            if not t._stop_gradient:
                arr = g._array if g is not None else _ones_like(t._array)
                leaf_grads[id(t)] = leaf_grads.get(id(t), 0) + arr
                leaf_tensors[id(t)] = t
            continue
        node = t._grad_node
        if g is None:
            if t._array.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {tuple(t._array.shape)}")
            g_arr = _ones_like(t._array)
        else:
            g_arr = g._array if isinstance(g, Tensor) else jnp.asarray(g)
        buf = buffers.setdefault(id(node), [None] * len(node.out_avals))
        cur = buf[t._out_index]
        buf[t._out_index] = g_arr if cur is None else cur + g_arr
        roots.append(node)

    nodes, pending = _collect_graph(roots)

    # the input-capture set for paddle.grad-style partial grads
    capture: Dict[int, Tensor] = {id(t): t for t in (inputs or [])}
    captured: Dict[int, object] = {}

    ready = deque(n for n in {id(r): r for r in roots}.values()
                  if pending.get(id(n), 0) == 0)
    # roots that still have pending consumers wait their turn
    processed = set()

    while ready:
        node = ready.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))

        buf = buffers.pop(id(node), [None] * len(node.out_avals))
        cots = []
        for aval, c in zip(node.out_avals, buf):
            if c is None:
                shape, dt = aval
                import numpy as _np
                import jax as _jx
                if jnp.issubdtype(dt, jnp.inexact):
                    c = jnp.zeros(shape, dt)
                else:
                    # integer/bool primal outputs take float0 cotangents
                    c = _np.zeros(shape, _jx.dtypes.float0)
            cots.append(c)
        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time "
                "(set retain_graph=True to allow this).")
        import jax as _jax
        cot_tree = _jax.tree_util.tree_unflatten(node.out_treedef, cots)
        in_grads = node.vjp_fn(cot_tree)
        if not retain_graph:
            node.vjp_fn = None

        for t, g in zip(node.inputs, in_grads):
            if g is None:
                # still a consumed edge: decrement the producer's pending count
                prod = t._grad_node
                if prod is not None:
                    pending[id(prod)] -= 1
                    if pending[id(prod)] == 0:
                        buffers.setdefault(id(prod),
                                           [None] * len(prod.out_avals))
                        ready.append(prod)
                continue
            # per-tensor gradient hooks
            if t._backward_hooks:
                gt = Tensor(g)
                for hook in list(t._backward_hooks.values()):
                    res = hook(gt)
                    if res is not None:
                        gt = res if isinstance(res, Tensor) else Tensor(res)
                g = gt._array
            if id(t) in capture:
                captured[id(t)] = captured.get(id(t), 0) + g
            prod = t._grad_node
            if prod is None:
                if not t._stop_gradient:
                    leaf_grads[id(t)] = leaf_grads.get(id(t), 0) + g
                    leaf_tensors[id(t)] = t
                continue
            pbuf = buffers.setdefault(id(prod), [None] * len(prod.out_avals))
            cur = pbuf[t._out_index]
            pbuf[t._out_index] = g if cur is None else cur + g
            pending[id(prod)] -= 1
            if pending[id(prod)] == 0:
                ready.append(prod)

    if accumulate_into_grad:
        for tid, g in leaf_grads.items():
            t = leaf_tensors[tid]
            if t.grad is None:
                t.grad = Tensor(g)
            else:
                t.grad = Tensor(t.grad._array + g)

    if inputs is not None:
        out = []
        for t in inputs:
            g = captured.get(id(t))
            if g is None and id(t) in leaf_grads:
                g = leaf_grads[id(t)]
            out.append(Tensor(g) if g is not None else None)
        return out
    return None


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad equivalent (reference: egr::Grad, backward.cc:808).

    ``create_graph`` is not supported on the eager tape (use the functional
    ``paddle_tpu.autograd`` transforms for higher-order grads).
    """
    if create_graph:
        raise NotImplementedError(
            "create_graph=True on the eager tape is unsupported; use "
            "paddle_tpu.autograd.grad/vjp (functional) for higher-order grads.")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = False
    grads = run_backward(list(outputs), list(grad_outputs),
                         retain_graph=retain_graph, inputs=list(inputs),
                         accumulate_into_grad=False)
    if not allow_unused:
        for t, g in zip(inputs, grads):
            if g is None:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused; pass "
                    "allow_unused=True to return None for it.")
    return grads
