"""Global autograd-recording switch.

Mirrors the reference's tracer on/off state (`paddle.no_grad`,
reference: python/paddle/fluid/dygraph/base.py no_grad_) but as a simple
nestable context manager / decorator.  When recording is off, ops execute
their raw jax computation with no tape nodes created — this is also the mode
used while tracing a compiled (``to_static``) step, where jax's own tracing
provides differentiation.
"""
from __future__ import annotations

import contextlib
import functools

_grad_enabled = [True]


def is_grad_enabled() -> bool:
    return _grad_enabled[0]


def set_grad_enabled(mode: bool):
    """Context manager *and* direct setter, as in the reference API."""
    return _GradScope(bool(mode))


class _GradScope(contextlib.AbstractContextManager):
    def __init__(self, mode):
        self._mode = mode
        self._prev = None
        # act immediately so `set_grad_enabled(False)` works without `with`
        self._prev = _grad_enabled[0]
        _grad_enabled[0] = mode

    def __exit__(self, *exc):
        _grad_enabled[0] = self._prev
        return False


class no_grad(contextlib.ContextDecorator):
    """``with paddle_tpu.no_grad(): ...`` or ``@paddle_tpu.no_grad()``."""

    def __enter__(self):
        self._prev = _grad_enabled[0]
        _grad_enabled[0] = False
        return self

    def __exit__(self, *exc):
        _grad_enabled[0] = self._prev
        return False

    def __call__(self, func=None):
        if func is None:
            return self
        @functools.wraps(func)
        def wrapper(*a, **k):
            with no_grad():
                return func(*a, **k)
        return wrapper


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _grad_enabled[0]
        _grad_enabled[0] = True
        return self

    def __exit__(self, *exc):
        _grad_enabled[0] = self._prev
        return False
