"""Eager op dispatch: raw jax fn -> Tensor-level op with tape recording.

This is the TPU-native replacement for the reference's generated per-op
dygraph functions (reference: paddle/fluid/eager/auto_code_generator/ — each
op got a generated forward that runs the phi kernel then wires a GradNode).
Here one generic ``call`` does both: run the raw ``jax.numpy`` computation,
and when autograd is recording, capture the op's pullback via ``jax.vjp``.

Raw op functions operate purely on jax arrays (so they are also directly
usable inside ``jit``/``grad`` traces); the Tensor-level wrappers produced by
``wrap_op`` are what ``paddle_tpu.ops`` exports.
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp

from . import dtype as _dtype_mod
from .grad_mode import is_grad_enabled
from .tensor import GradNode, Tensor

_TensorLeaf = lambda x: isinstance(x, Tensor)
_amp = None  # lazily bound paddle_tpu.amp module
_flags_fast_get = None  # lazily bound utils.flags.fast_get


def _is_diff(x) -> bool:
    return (isinstance(x, Tensor) and not x._stop_gradient
            and _dtype_mod.is_inexact(x._array.dtype))


def call(raw_fn: Callable, *args, name: str = None, **kwargs):
    """Execute ``raw_fn`` over unwrapped args; record a GradNode if needed."""
    leaves, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=_TensorLeaf)

    diff_idx = []
    if is_grad_enabled():
        diff_idx = [i for i, l in enumerate(leaves) if _is_diff(l)]

    arrays = [l._array if isinstance(l, Tensor) else l for l in leaves]

    # AMP: cast fp32 inputs of white-listed ops to the active amp dtype
    global _amp
    if _amp is None:
        from .. import amp as _amp_mod
        _amp = _amp_mod
    if _amp.amp_state()["enable"]:
        arrays = _amp.amp_cast_inputs(name, arrays)

    if not diff_idx:
        a2, k2 = jax.tree_util.tree_unflatten(treedef, arrays)
        try:
            out = raw_fn(*a2, **k2)
        except Exception as e:
            _annotate_op_error(e, name, arrays)
            raise
        return _wrap_outputs(out, None, op_name=name)

    diff_arrays = [arrays[i] for i in diff_idx]

    def f(*dargs):
        buf = list(arrays)
        for i, a in zip(diff_idx, dargs):
            buf[i] = a
        a2, k2 = jax.tree_util.tree_unflatten(treedef, buf)
        return raw_fn(*a2, **k2)

    try:
        out, vjp_fn = jax.vjp(f, *diff_arrays)
    except Exception as e:
        _annotate_op_error(e, name, arrays)
        raise

    out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
    node = GradNode(
        vjp_fn=vjp_fn,
        inputs=[leaves[i] for i in diff_idx],
        out_avals=[(tuple(o.shape), o.dtype) for o in out_leaves],
        name=name or getattr(raw_fn, "__name__", "op"),
        out_treedef=out_treedef,
    )
    return _wrap_outputs(out, node, op_name=name)


def _annotate_op_error(e: BaseException, name, arrays):
    """Rich error context (reference: PADDLE_ENFORCE op-attributed errors,
    phi/core/enforce.h): attach the failing operator and its input
    shapes/dtypes to the exception without altering its type."""
    try:
        shapes = ", ".join(
            f"{tuple(a.shape)}:{a.dtype}" if hasattr(a, "shape") else
            repr(a)[:32]
            for a in arrays[:6])
        if len(arrays) > 6:
            shapes += f", +{len(arrays) - 6} more"
        note = (f"[paddle_tpu] operator: {name or '<unnamed>'} "
                f"(inputs: {shapes})")
        if hasattr(e, "add_note"):
            e.add_note(note)
        else:   # python < 3.11: emulate PEP 678 (__notes__ list)
            e.__notes__ = list(getattr(e, "__notes__", [])) + [note]
    except Exception:
        pass  # never mask the original error


def _wrap_outputs(out, node, op_name=None):
    out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
    _maybe_check_nan_inf(out_leaves, op_name)
    wrapped = []
    for i, o in enumerate(out_leaves):
        t = Tensor(o, stop_gradient=True)
        # integer/bool outputs (argmax, indices, ...) never carry grad
        if node is not None and _dtype_mod.is_inexact(o.dtype):
            t._grad_node = node
            t._out_index = i
            t._stop_gradient = False
        wrapped.append(t)
    return jax.tree_util.tree_unflatten(out_treedef, wrapped)


def _maybe_check_nan_inf(out_leaves, op_name):
    """FLAGS_check_nan_inf: validate every eager op output is finite
    (reference: operator.cc:1252 -> nan_inf_utils_detail CheckVarHasNanOrInf
    — per-op attribution of the first non-finite value).  Eager arrays only;
    traced values are covered by jax debug_nans."""
    global _flags_fast_get
    if _flags_fast_get is None:
        from ..utils.flags import fast_get as _flags_fast_get_fn
        _flags_fast_get = _flags_fast_get_fn
    # direct registry read: this gate sits on EVERY eager op dispatch
    if not _flags_fast_get("check_nan_inf"):
        return
    for o in out_leaves:
        if isinstance(o, jax.core.Tracer) or not hasattr(o, "dtype"):
            continue
        if not _dtype_mod.is_inexact(o.dtype):
            continue
        finite = bool(jnp.all(jnp.isfinite(o)))
        if not finite:
            n_nan = int(jnp.sum(jnp.isnan(o)))
            n_inf = int(jnp.sum(jnp.isinf(o)))
            raise FloatingPointError(
                f"Operator {op_name or '<unknown>'} output contains "
                f"{n_nan} NaN / {n_inf} Inf values "
                f"(shape {tuple(o.shape)}, dtype {o.dtype}). "
                "Set FLAGS_check_nan_inf=0 to disable this check.")


def wrap_op(raw_fn: Callable = None, *, name: str = None):
    """Turn a raw jax-array function into an eager Tensor op."""
    def deco(fn):
        op_name = name or fn.__name__

        @functools.wraps(fn)
        def tensor_op(*args, **kwargs):
            return call(fn, *args, name=op_name, **kwargs)

        tensor_op.raw = fn
        return tensor_op

    if raw_fn is not None:
        return deco(raw_fn)
    return deco


def shadow(t: Tensor) -> Tensor:
    """Snapshot of a tensor's autograd identity, for in-place ops.

    In-place ops redirect the original object's node pointer; recording the
    original object as a node input would create a self-loop in the graph.
    The shadow preserves the pre-mutation (array, node, index, hooks) so the
    backward engine routes gradients exactly as if the mutation were the
    functional op it lowers to.

    A *leaf* that requires grad cannot be mutated in place while recording —
    its accumulated .grad would land on the shadow, invisible to the user
    (same restriction as the reference/torch eager mode).
    """
    if (is_grad_enabled() and t._grad_node is None
            and not t._stop_gradient
            and _dtype_mod.is_inexact(t._array.dtype)):
        raise RuntimeError(
            "a leaf Tensor with stop_gradient=False cannot be modified "
            "in-place while autograd is recording; use paddle_tpu.no_grad() "
            "or operate on a non-leaf (e.g. t * 1).")
    s = Tensor.__new__(Tensor)
    s._array = t._array
    s._stop_gradient = t._stop_gradient
    s._grad_node = t._grad_node
    s._out_index = t._out_index
    s.grad = None
    s.name = t.name
    s._backward_hooks = t._backward_hooks
    s.persistable = False
    return s


def assign_inplace(t: Tensor, new: Tensor) -> Tensor:
    """Redirect ``t`` to the functional result ``new`` (single home for the
    in-place redirect used by methods._inplace and manipulation.setitem)."""
    t._array = new._array
    t._grad_node = new._grad_node
    t._out_index = new._out_index
    if new._grad_node is not None:
        t._stop_gradient = False
    return t


def unwrap(x):
    """Tensor -> jax array (idempotent for arrays/pytrees)."""
    return jax.tree_util.tree_map(
        lambda l: l._array if isinstance(l, Tensor) else l, x,
        is_leaf=_TensorLeaf)
