"""Dtype registry.

The reference exposes paddle dtypes through ``paddle.float32`` etc. and a
VarType enum (reference: paddle/fluid/framework/framework.proto:117).  Here a
dtype is simply a ``jnp.dtype``; this module provides the canonical aliases,
name normalisation and the default-dtype switch
(reference: python/paddle/framework/framework.py set_default_dtype).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_ALIASES = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "fp16": float16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "float": float32,
    "double": float64,
    "int": int32,
    "complex64": complex64,
    "complex128": complex128,
}

_default_dtype = [np.dtype("float32")]


def set_default_dtype(d):
    _default_dtype[0] = convert_dtype(d)


def get_default_dtype():
    return _default_dtype[0]


def convert_dtype(d):
    """Normalise any dtype spec (str alias, np/jnp dtype, python type) to np.dtype."""
    if d is None:
        return None
    if isinstance(d, str):
        if d in _ALIASES:
            return np.dtype(_ALIASES[d])
        return np.dtype(d)
    return np.dtype(d)


def is_floating(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.floating)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.complexfloating)


def is_inexact(dtype) -> bool:
    return is_floating(dtype) or is_complex(dtype)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.integer)


def x64_scope(enable: bool):
    """Version-portable ``jax.enable_x64`` context manager.

    The top-level ``jax.enable_x64`` re-export was removed in newer jax;
    ``jax.experimental.enable_x64`` is the surviving spelling.  Pallas
    kernels and the CE loss trace under ``x64_scope(False)`` because
    mosaic cannot lower i64/f64 even though the global x64 mode is on.
    """
    import jax
    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:
        from jax.experimental import enable_x64 as ctx
    return ctx(enable)
