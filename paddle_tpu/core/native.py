"""ctypes loader for the native C++ runtime (csrc/).

Builds csrc/libpaddle_tpu_native.so on first use (g++ is in the image; no
pybind11 — plain C ABI).  Every consumer has a pure-Python fallback, so a
missing toolchain degrades gracefully.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lib = None
_lock = threading.Lock()
_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "libpaddle_tpu_native.so")


def load():
    """Return the loaded library or None when unavailable."""
    global _lib
    if _lib is not None:
        return _lib if _lib is not False else None
    with _lock:
        if _lib is not None:
            return _lib if _lib is not False else None
        try:
            if not os.path.exists(_SO) or (
                    os.path.getmtime(_SO) < max(
                        os.path.getmtime(os.path.join(_CSRC, f))
                        for f in ("tcp_store.cpp", "shm_queue.cpp"))):
                subprocess.run(["make", "-s", "-C", _CSRC],
                               check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_SO)
        except Exception:
            _lib = False
            return None
        # signatures
        lib.tcp_store_server_create.restype = ctypes.c_void_p
        lib.tcp_store_server_create.argtypes = [ctypes.c_int]
        lib.tcp_store_server_port.restype = ctypes.c_int
        lib.tcp_store_server_port.argtypes = [ctypes.c_void_p]
        lib.tcp_store_server_destroy.argtypes = [ctypes.c_void_p]
        lib.tcp_store_client_create.restype = ctypes.c_void_p
        lib.tcp_store_client_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.tcp_store_client_create_t.restype = ctypes.c_void_p
        lib.tcp_store_client_create_t.argtypes = [ctypes.c_char_p,
                                                  ctypes.c_int, ctypes.c_int]
        lib.tcp_store_client_destroy.argtypes = [ctypes.c_void_p]
        lib.tcp_store_set.restype = ctypes.c_int
        lib.tcp_store_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_int]
        lib.tcp_store_get.restype = ctypes.c_longlong
        lib.tcp_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_longlong,
                                      ctypes.c_int]
        lib.tcp_store_add.restype = ctypes.c_longlong
        lib.tcp_store_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_longlong]
        lib.shm_queue_create.restype = ctypes.c_void_p
        lib.shm_queue_create.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
        lib.shm_queue_open.restype = ctypes.c_void_p
        lib.shm_queue_open.argtypes = [ctypes.c_char_p]
        lib.shm_queue_push.restype = ctypes.c_int
        lib.shm_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_longlong]
        lib.shm_queue_pop.restype = ctypes.c_longlong
        lib.shm_queue_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_longlong]
        lib.shm_queue_size.restype = ctypes.c_longlong
        lib.shm_queue_size.argtypes = [ctypes.c_void_p]
        lib.shm_queue_close.argtypes = [ctypes.c_void_p]
        lib.shm_queue_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def available() -> bool:
    return load() is not None
