"""High-level API (reference surface: python/paddle/hapi/model.py —
Model.prepare/fit/evaluate/predict at model.py:907,1486,1557; callbacks).

TPU-native: fit() drives a jitted TrainStep (one XLA program per step) rather
than the reference's per-op dygraph/static adapters.
"""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader, Dataset
from ..jit import TrainStep, functional_call
from ..metric import Metric
from ..observability import hbm as _hbm
from ..observability import liveness as _liveness
from ..observability import registry as _metrics

# liveness beacon over one fit batch (train_batch INCLUDES the loss
# fetch — a real device sync, so a wedged device step stalls here even
# when dispatch itself returned)
_liveness.declare_beacon(
    "train.fit_batch", "one hapi fit batch: compiled step dispatch + "
    "the loss fetch device sync", deadline=600.0)

__all__ = ["Model", "Callback", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler", "summary", "flops"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {v:.4f}" if isinstance(v, float) else
                              f"{k}: {v}" for k, v in (logs or {}).items())
            print(f"Epoch {self._epoch} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            items = ", ".join(f"{k}: {v:.4f}" if isinstance(v, float) else
                              f"{k}: {v}" for k, v in (logs or {}).items())
            print(f"Epoch {epoch} done in {dt:.1f}s: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/epoch_{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="min", patience=0, min_delta=0,
                 baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self.best = baseline
        self.wait = 0
        self.stop_training = False

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        better = (self.best is None
                  or (self.mode == "min" and cur < self.best - self.min_delta)
                  or (self.mode == "max" and cur > self.best + self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch


class Model:
    """reference parity: python/paddle/hapi/model.py:907."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        else:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]

    def _ensure_train_step(self):
        if self._train_step is None:
            def loss_fn(logits, *rest):
                raise RuntimeError  # replaced per-batch below
            self._train_step = None  # built lazily in train_batch

    def train_batch(self, inputs, labels=None, update=True):
        """One eager-compiled step (reference: model.py:1045)."""
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) \
            else [labels]
        if self._train_step is None:
            self._train_step = TrainStep(self.network, self._loss,
                                         self._optimizer,
                                         num_inputs=len(inputs))
        loss = self._train_step(*inputs, *(labels or []))
        metrics_out = []
        return [float(loss.numpy())], metrics_out

    def eval_batch(self, inputs, labels=None):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) \
            else [labels]
        self.network.eval()
        if self._train_step is not None:
            self._train_step.sync_to_model()
        outs = self.network(*inputs)
        outs_t = outs if isinstance(outs, (list, tuple)) else [outs]
        loss = None
        if self._loss is not None and labels:
            loss = self._loss(*(list(outs_t) + list(labels)))
        metric_res = []
        for m in self._metrics:
            c = m.compute(*(list(outs_t) + list(labels or [])))
            metric_res.append(m.update(c))
        self.network.train()
        if loss is not None:
            return [float(loss.numpy())], metric_res
        return metric_res

    def predict_batch(self, inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.network.eval()
        if self._train_step is not None:
            self._train_step.sync_to_model()
        out = self.network(*inputs)
        self.network.train()
        return out

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        """reference parity: model.py:1557."""
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        eval_loader = None
        if eval_data is not None:
            eval_loader = (DataLoader(eval_data, batch_size=batch_size)
                           if isinstance(eval_data, Dataset) else eval_data)
        cbs = list(callbacks or [])
        if verbose:
            cbs.append(ProgBarLogger(log_freq, verbose))
        if save_dir:
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        for cb in cbs:
            cb.set_model(self)
            cb.set_params({"epochs": epochs, "verbose": verbose})
        for cb in cbs:
            cb.on_train_begin()
        # fit-loop telemetry (OBSERVABILITY.md): per-batch wall time here
        # includes the loss fetch in train_batch — a real device sync — so
        # unlike train.step_seconds (dispatch only) this is end-to-end
        m_batch = _metrics.histogram("train.batch_seconds")
        m_loss = _metrics.gauge("train.loss")
        m_samples = _metrics.counter("train.samples")
        m_tokens = _metrics.counter("train.tokens")
        b_batch = _liveness.beacon("train.fit_batch")
        it_count = 0
        for epoch in range(epochs):
            for cb in cbs:
                cb.on_epoch_begin(epoch)
            logs = {}
            for step, batch in enumerate(train_loader):
                ins, lbls = self._split_batch(batch)
                t0 = time.perf_counter()
                with b_batch:
                    losses, _ = self.train_batch(ins, lbls)
                m_batch.observe(time.perf_counter() - t0)
                m_loss.set(losses[0])
                shape = getattr(ins[0], "shape", None)
                if shape:
                    m_samples.inc(int(shape[0]))
                    if len(shape) >= 2:
                        m_tokens.inc(int(shape[0]) * int(shape[1]))
                logs = {"loss": losses[0]}
                # HBM-ledger sample at the batch boundary (the loss
                # fetch above was a real device sync, so live_arrays is
                # settled here); one global None check while disarmed
                _hbm.maybe_sample("train.batch")
                for cb in cbs:
                    cb.on_train_batch_end(step, logs)
                it_count += 1
                # stop_training is honored PER BATCH: a callback tripping
                # mid-epoch (e.g. DivergenceMonitor with its rollback ring
                # exhausted) must not keep training — and then checkpoint —
                # a contaminated state for the rest of a long epoch
                if self.stop_training or (num_iters and
                                          it_count >= num_iters):
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update(eval_logs)
                for cb in cbs:
                    cb.on_eval_end(eval_logs)
            for cb in cbs:
                cb.on_epoch_end(epoch, logs)
            if self.stop_training or (num_iters and it_count >= num_iters):
                break
        for cb in cbs:
            cb.on_train_end()
        if self._train_step is not None:
            self._train_step.sync_to_model()

    @staticmethod
    def _split_batch(batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return [batch[0]], list(batch[1:])
            return [batch[0]], []
        return [batch], []

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = (DataLoader(eval_data, batch_size=batch_size,
                             num_workers=num_workers)
                  if isinstance(eval_data, Dataset) else eval_data)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            ins, lbls = self._split_batch(batch)
            res = self.eval_batch(ins, lbls)
            if isinstance(res, tuple) and len(res) == 2 and res[0]:
                losses.append(res[0][0])
        logs = {}
        if losses:
            logs["eval_loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs["eval_" + m.name()] = m.accumulate()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = (DataLoader(test_data, batch_size=batch_size,
                             num_workers=num_workers)
                  if isinstance(test_data, Dataset) else test_data)
        outs = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            outs.append(self.predict_batch(ins))
        return outs

    def save(self, path, training=True):
        from .. import framework
        if self._train_step is not None:
            self._train_step.sync_to_model()
        framework.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            framework.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from .. import framework
        sd = framework.load(path + ".pdparams")
        self.network.set_state_dict(sd)
        import os
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(path + ".pdopt")):
            self._optimizer.set_state_dict(framework.load(path + ".pdopt"))
        self._train_step = None

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtype)


def summary(net, input_size=None, dtypes=None):
    """Parameter-count summary (reference: hapi/model_summary.py)."""
    total = 0
    trainable = 0
    lines = [f"{'Layer':45s} {'Param #':>12s}"]
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        lines.append(f"{name[:45]:45s} {n:12d}")
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size=None, inputs=None, dtypes=None, custom_ops=None,
          print_detail=False):
    """Model FLOPs (reference: hapi/dynamic_flops.py paddle.flops).

    TPU-native: instead of per-layer-type formulas, the forward is traced
    and compiled and XLA's own cost analysis reports the FLOPs of the
    compiled graph (fusions included).  Limitation: custom-call regions
    (Pallas kernels) are opaque to XLA cost analysis and count as 0;
    ``custom_ops`` hooks are therefore not supported — measure such models
    with the profiler instead (PERF.md methodology).

    ``dtypes``: one dtype string or a list matching input_size (default
    float32) — integer-input models (Embedding-first) need e.g. "int32".
    """
    import jax

    from ..core.dtype import convert_dtype

    if custom_ops is not None:
        raise NotImplementedError(
            "flops(custom_ops=...) is not supported on the TPU build: XLA "
            "cost analysis counts compiled HLO only (custom Pallas calls "
            "are opaque); use jax.profiler / PERF.md methodology instead")
    if inputs is None:
        if input_size is None:
            raise ValueError("flops needs input_size=[shape, ...] or inputs")
        shapes = input_size if isinstance(input_size[0], (list, tuple)) \
            else [input_size]
        if dtypes is None:
            dts = ["float32"] * len(shapes)
        elif isinstance(dtypes, str):
            dts = [dtypes] * len(shapes)
        else:
            dts = list(dtypes)
            if len(dts) != len(shapes):
                raise ValueError(
                    f"dtypes has {len(dts)} entries for {len(shapes)} "
                    "input shapes")
        inputs = [jax.ShapeDtypeStruct(tuple(int(d) for d in s),
                                       convert_dtype(dt))
                  for s, dt in zip(shapes, dts)]
    else:
        inputs = [i._array if hasattr(i, "_array") else i for i in inputs]
    was_training = getattr(net, "training", True)
    net.eval()
    try:
        state = net.functional_state()

        def fwd(state, *args):
            out, _ = functional_call(net, state, *args)
            return out

        compiled = jax.jit(fwd).lower(state, *inputs).compile()
    finally:
        if was_training:
            net.train()
    # ONE cost_analysis parser for the whole repo (incl. the 0.4.x
    # list-shape compat): observability.costs — the same extraction the
    # `programs` CLI and TPU506 run on the canonical registry.  strict:
    # a RAISING cost_analysis must propagate (this API returns a bare
    # int — a swallowed failure would read as "0 FLOPs", a plausible
    # wrong answer with no degradation channel)
    from ..observability.costs import cost_analysis_dict
    ca = cost_analysis_dict(compiled, strict=True)
    total = int(ca.get("flops", 0))
    if print_detail:
        print(f"FLOPs (XLA cost analysis): {total:,}")
        if "bytes accessed" in ca:
            print(f"Bytes accessed: {int(ca['bytes accessed']):,}")
    return total
