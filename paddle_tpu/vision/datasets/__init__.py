"""Vision datasets (reference surface: python/paddle/vision/datasets/).

Zero-egress environment: when download is unavailable, MNIST/Cifar fall back
to deterministic synthetic data with the real shapes/cardinality so training
pipelines and benchmarks run unchanged.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "ImageFolder",
           "DatasetFolder"]


class MNIST(Dataset):
    NUM_TRAIN = 60000
    NUM_TEST = 10000

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None,
                 synthetic_size=None):
        self.mode = mode
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path,
                                              synthetic_size)

    def _load(self, image_path, label_path, synthetic_size):
        if image_path and label_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                _, num, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), np.uint8).reshape(
                    num, rows, cols)
            with gzip.open(label_path, "rb") as f:
                _, num = struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
            return images, labels
        # synthetic fallback (deterministic)
        n = synthetic_size or (4096 if self.mode == "train" else 1024)
        rng = np.random.RandomState(42 if self.mode == "train" else 43)
        images = (rng.rand(n, 28, 28) * 255).astype(np.uint8)
        labels = rng.randint(0, 10, n).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :] / 255.0
        label = np.asarray([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(self.images[idx])
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        self.mode = mode
        self.transform = transform
        n = synthetic_size or (4096 if mode == "train" else 1024)
        rng = np.random.RandomState(44 if mode == "train" else 45)
        self.images = (rng.rand(n, 3, 32, 32) * 255).astype(np.uint8)
        self.labels = rng.randint(0, self.NUM_CLASSES, n).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(np.transpose(self.images[idx], (1, 2, 0)))
        return img, np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    NUM_CLASSES = 10


class Cifar100(_CifarBase):
    NUM_CLASSES = 100


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".jpg", ".jpeg", ".png", ".bmp", ".npy")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for fname in sorted(os.listdir(d)):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(d, fname),
                                         self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError:
            raise RuntimeError("PIL unavailable; use .npy images")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=None, transform=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".jpg", ".jpeg", ".png", ".bmp", ".npy")
        self.samples = []
        for dirpath, _, fnames in os.walk(root):
            for fname in sorted(fnames):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(dirpath, fname), -1))
        self.loader = loader or DatasetFolder._default_loader

    def __getitem__(self, idx):
        path, _ = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return (img,)
