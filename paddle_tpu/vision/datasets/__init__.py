"""Vision datasets (reference surface: python/paddle/vision/datasets/).

Zero-egress environment: when download is unavailable, MNIST/Cifar fall back
to deterministic synthetic data with the real shapes/cardinality so training
pipelines and benchmarks run unchanged.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "ImageFolder",
           "DatasetFolder", "Flowers", "VOC2012"]


class MNIST(Dataset):
    NUM_TRAIN = 60000
    NUM_TEST = 10000

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None,
                 synthetic_size=None):
        self.mode = mode
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path,
                                              synthetic_size)

    def _load(self, image_path, label_path, synthetic_size):
        if image_path and label_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                _, num, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), np.uint8).reshape(
                    num, rows, cols)
            with gzip.open(label_path, "rb") as f:
                _, num = struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
            return images, labels
        # synthetic fallback (deterministic)
        n = synthetic_size or (4096 if self.mode == "train" else 1024)
        rng = np.random.RandomState(42 if self.mode == "train" else 43)
        images = (rng.rand(n, 28, 28) * 255).astype(np.uint8)
        labels = rng.randint(0, 10, n).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :] / 255.0
        label = np.asarray([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(self.images[idx])
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        self.mode = mode
        self.transform = transform
        n = synthetic_size or (4096 if mode == "train" else 1024)
        rng = np.random.RandomState(44 if mode == "train" else 45)
        self.images = (rng.rand(n, 3, 32, 32) * 255).astype(np.uint8)
        self.labels = rng.randint(0, self.NUM_CLASSES, n).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(np.transpose(self.images[idx], (1, 2, 0)))
        return img, np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    NUM_CLASSES = 10


class Cifar100(_CifarBase):
    NUM_CLASSES = 100


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".jpg", ".jpeg", ".png", ".bmp", ".npy")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for fname in sorted(os.listdir(d)):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(d, fname),
                                         self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError:
            raise RuntimeError("PIL unavailable; use .npy images")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


#: reference flowers.py:40 — the official readme's tstid flags TEST data
#: but is larger than trnid, so the reference swaps them; kept for parity
_FLOWERS_MODE_FLAG = {"train": "tstid", "test": "trnid", "valid": "valid"}


class Flowers(Dataset):
    """Oxford 102 Flowers (reference: python/paddle/vision/datasets/
    flowers.py:43).  Parses the REAL on-disk formats: ``102flowers.tgz``
    (jpg/image_%05d.jpg members, read straight from the tar — no
    extractall), ``imagelabels.mat`` and ``setid.mat`` (MATLAB v5 via
    scipy.io).  Without files (zero-egress), falls back to deterministic
    synthetic data with the real cardinality/label semantics."""

    NUM_CLASSES = 102

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None,
                 synthetic_size=None):
        mode = mode.lower()
        assert mode in ("train", "valid", "test"), mode
        self.mode = mode
        self.transform = transform
        self._tar = None
        flag = _FLOWERS_MODE_FLAG[mode]
        if data_file and label_file and setid_file \
                and os.path.exists(data_file):
            import tarfile

            import scipy.io as scio
            # 1-based image ids; labels[i-1] is image i's class (1..102)
            self.labels = scio.loadmat(label_file)["labels"][0]
            self.indexes = scio.loadmat(setid_file)[flag][0]
            self._tar = tarfile.open(data_file)
        else:
            n = synthetic_size or {"train": 512, "valid": 128,
                                   "test": 128}[mode]
            rng = np.random.RandomState(
                {"train": 46, "valid": 47, "test": 48}[mode])
            self.labels = rng.randint(1, self.NUM_CLASSES + 1,
                                      max(n * 2, n + 1))
            self.indexes = np.arange(1, n + 1)
            self._synth = (rng.rand(n, 64, 64, 3) * 255).astype(np.uint8)

    def _image(self, index):
        if self._tar is not None:
            member = "jpg/image_%05d.jpg" % index
            from PIL import Image
            import io as _io
            data = self._tar.extractfile(member).read()
            return np.asarray(Image.open(_io.BytesIO(data)).convert("RGB"))
        return self._synth[index - 1]

    def __getitem__(self, idx):
        index = int(self.indexes[idx])
        label = np.array([self.labels[index - 1]]).astype(np.int64)
        image = self._image(index)
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self.indexes)


#: reference voc2012.py:31-38 — member paths inside the VOC tar and the
#: (deliberately shuffled) mode->set-file mapping
_VOC_SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
_VOC_DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
_VOC_LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
_VOC_MODE_FLAG = {"train": "trainval", "test": "train", "valid": "val"}


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (reference: python/paddle/vision/
    datasets/voc2012.py:40).  Parses the REAL tar layout: the split's
    ImageSets/Segmentation/<flag>.txt member lists image ids; JPEGImages
    and SegmentationClass members are read straight from the tar.
    Returns (image HWC uint8, label HW uint8).  Synthetic fallback keeps
    the shapes and the 21-class label range."""

    NUM_CLASSES = 21

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        mode = mode.lower()
        assert mode in ("train", "valid", "test"), mode
        self.mode = mode
        self.transform = transform
        self.flag = _VOC_MODE_FLAG[mode]
        self._tar = None
        if data_file and os.path.exists(data_file):
            import tarfile

            self._tar = tarfile.open(data_file)
            listing = self._tar.extractfile(
                _VOC_SET_FILE.format(self.flag)).read().decode()
            self.ids = [ln.strip() for ln in listing.splitlines()
                        if ln.strip()]
        else:
            n = synthetic_size or {"train": 128, "valid": 64,
                                   "test": 64}[mode]
            rng = np.random.RandomState(
                {"train": 49, "valid": 50, "test": 51}[mode])
            self.ids = ["synthetic_%06d" % i for i in range(n)]
            self._synth_img = (rng.rand(n, 64, 64, 3) * 255).astype(np.uint8)
            self._synth_lbl = rng.randint(
                0, self.NUM_CLASSES, (n, 64, 64)).astype(np.uint8)

    def _member(self, template, image_id):
        from PIL import Image
        import io as _io
        data = self._tar.extractfile(template.format(image_id)).read()
        return Image.open(_io.BytesIO(data))

    def __getitem__(self, idx):
        image_id = self.ids[idx]
        if self._tar is not None:
            image = np.asarray(self._member(_VOC_DATA_FILE,
                                            image_id).convert("RGB"))
            # palette PNG: pixel values ARE the class ids (+255 ignore)
            label = np.asarray(self._member(_VOC_LABEL_FILE, image_id))
        else:
            image = self._synth_img[idx]
            label = self._synth_lbl[idx]
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self.ids)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=None, transform=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".jpg", ".jpeg", ".png", ".bmp", ".npy")
        self.samples = []
        for dirpath, _, fnames in os.walk(root):
            for fname in sorted(fnames):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(dirpath, fname), -1))
        self.loader = loader or DatasetFolder._default_loader

    def __getitem__(self, idx):
        path, _ = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return (img,)
