"""Vision transforms (reference surface: python/paddle/vision/transforms/) —
numpy/CHW-based functional + composable class transforms."""
from __future__ import annotations

import numbers
import random as _pyrandom

import numpy as np

from ...core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


def _to_numpy(img):
    if isinstance(img, Tensor):
        return np.asarray(img._array)
    return np.asarray(img)


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_numpy(img).astype(np.float32)
        if self.data_format == "CHW":
            n = arr.shape[0]
            mean = self.mean[:n].reshape(-1, 1, 1)
            std = self.std[:n].reshape(-1, 1, 1)
        else:
            n = arr.shape[-1]
            mean = self.mean[:n]
            std = self.std[:n]
        out = (arr - mean) / std
        if isinstance(img, Tensor):
            return Tensor(out)
        return out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        arr = _to_numpy(img)
        import jax
        import jax.numpy as jnp
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            shape = (arr.shape[0],) + tuple(self.size)
        elif arr.ndim == 3:
            shape = tuple(self.size) + (arr.shape[-1],)
        else:
            shape = tuple(self.size)
        out = np.asarray(jax.image.resize(jnp.asarray(arr, jnp.float32), shape,
                                          method="bilinear"))
        return out.astype(arr.dtype) if arr.dtype != np.uint8 else \
            np.clip(out, 0, 255).astype(np.uint8)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        arr = _to_numpy(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        if chw:
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _to_numpy(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if self.padding:
            p = self.padding
            pads = ((0, 0), (p, p), (p, p)) if chw else \
                ((p, p), (p, p)) + ((0, 0),) * (arr.ndim - 2)
            arr = np.pad(arr, pads)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i = _pyrandom.randint(0, max(h - th, 0))
        j = _pyrandom.randint(0, max(w - tw, 0))
        if chw:
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if _pyrandom.random() < self.prob:
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
            return arr[:, :, ::-1].copy() if chw else arr[:, ::-1].copy()
        return arr


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if _pyrandom.random() < self.prob:
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
            return arr[:, ::-1, :].copy() if chw else arr[::-1].copy()
        return arr


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        arr = _to_numpy(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        area = h * w
        for _ in range(10):
            target_area = area * _pyrandom.uniform(*self.scale)
            ar = _pyrandom.uniform(*self.ratio)
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if tw <= w and th <= h:
                i = _pyrandom.randint(0, h - th)
                j = _pyrandom.randint(0, w - tw)
                crop = arr[:, i:i + th, j:j + tw] if chw else arr[i:i + th, j:j + tw]
                return self._resize._apply_image(crop)
        return self._resize._apply_image(arr)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


# functional aliases
def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format, to_rgb)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    arr = _to_numpy(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
    return arr[:, :, ::-1].copy() if chw else arr[:, ::-1].copy()


def vflip(img):
    arr = _to_numpy(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
    return arr[:, ::-1, :].copy() if chw else arr[::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)
