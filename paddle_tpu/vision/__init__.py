"""paddle_tpu.vision (reference surface: python/paddle/vision/)."""
from . import datasets, models, ops, transforms  # noqa: F401
