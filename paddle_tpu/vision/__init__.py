"""paddle_tpu.vision (reference surface: python/paddle/vision/)."""
from . import datasets, models, transforms  # noqa: F401
