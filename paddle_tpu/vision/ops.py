"""paddle.vision.ops — detection / region operators (reference surface:
python/paddle/vision/ops.py: yolo_box:253, deform_conv2d:430, psroi_pool:918,
roi_pool:1033, roi_align:1160, nms:1376, ConvNormActivation:1322; CUDA
kernels under paddle/fluid/operators/detection/).

TPU-native design notes:
* RoI ops are computed as dense masked reductions / bilinear gathers over
  the feature map — static shapes, no data-dependent loops, so XLA can fuse
  and tile them (the reference's CUDA kernels thread per output bin; here
  the "bins" are a broadcast dimension).
* nms keeps the classic greedy loop but as a bounded lax.while_loop over a
  fixed-size box set with a suppression mask — compilable, O(K^2) IoU matrix
  computed once on the MXU-friendly path.
* deform_conv2d gathers bilinear samples per kernel tap then contracts with
  the weights in one einsum (the im2col-with-offsets formulation).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.dispatch import wrap_op

__all__ = ["yolo_box", "yolo_loss", "deform_conv2d", "DeformConv2D",
           "roi_align", "RoIAlign", "roi_pool", "RoIPool",
           "psroi_pool", "PSRoIPool", "nms", "ConvNormActivation",
           "read_file", "decode_jpeg"]


# ---------------------------------------------------------------------------
# YOLO
# ---------------------------------------------------------------------------

@wrap_op
def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head outputs into boxes + scores
    (reference: vision/ops.py yolo_box:253, operators/detection/yolo_box_op).

    x: (N, C, H, W) with C = an*(5+class_num); img_size: (N, 2) [h, w].
    Returns (boxes (N, H*W*an, 4) xyxy in image coords, scores
    (N, H*W*an, class_num)).
    """
    n, c, h, w = x.shape
    an = len(anchors) // 2
    anchors_a = jnp.asarray(np.asarray(anchors, np.float32).reshape(an, 2))
    if iou_aware:
        ioup = jax.nn.sigmoid(x[:, :an].reshape(n, an, 1, h, w))
        x = x[:, an:]
    pred = x.reshape(n, an, 5 + class_num, h, w)
    gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    sxy = jnp.float32(scale_x_y)
    bias = -0.5 * (sxy - 1.0)
    cx = (jax.nn.sigmoid(pred[:, :, 0]) * sxy + bias + gx) / w
    cy = (jax.nn.sigmoid(pred[:, :, 1]) * sxy + bias + gy) / h
    input_h = jnp.float32(downsample_ratio * h)
    input_w = jnp.float32(downsample_ratio * w)
    bw = jnp.exp(pred[:, :, 2]) * anchors_a[None, :, 0, None, None] / input_w
    bh = jnp.exp(pred[:, :, 3]) * anchors_a[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(pred[:, :, 4:5])
    if iou_aware:
        conf = conf ** (1.0 - iou_aware_factor) * \
            ioup ** iou_aware_factor
    probs = jax.nn.sigmoid(pred[:, :, 5:]) * conf
    # zero-out low-confidence boxes like the reference kernel
    keep = (conf > conf_thresh).astype(jnp.float32)
    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x0 = (cx - bw * 0.5) * img_w
    y0 = (cy - bh * 0.5) * img_h
    x1 = (cx + bw * 0.5) * img_w
    y1 = (cy + bh * 0.5) * img_h
    if clip_bbox:
        x0 = jnp.clip(x0, 0.0, img_w - 1.0)
        y0 = jnp.clip(y0, 0.0, img_h - 1.0)
        x1 = jnp.clip(x1, 0.0, img_w - 1.0)
        y1 = jnp.clip(y1, 0.0, img_h - 1.0)
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1) * keep[..., None] \
        .reshape(n, an, h, w, 1)
    scores = (probs * keep).transpose(0, 1, 3, 4, 2)
    return (boxes.reshape(n, an * h * w, 4),
            scores.reshape(n, an * h * w, class_num))


@wrap_op
def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0):
    """YOLOv3 training loss (reference: vision/ops.py yolo_loss:43,
    operators/detection/yolo_loss_op.h): coordinate + objectness + class
    losses with best-anchor target assignment and ignore-threshold masking.

    x: (N, C, H, W); gt_box: (N, B, 4) [cx, cy, w, h] normalized to image;
    gt_label: (N, B) int; returns per-image loss (N,).
    """
    n, c, h, w = x.shape
    an_all = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_idx = np.asarray(anchor_mask, np.int64)
    an = len(mask_idx)
    nb = gt_box.shape[1]
    pred = x.reshape(n, an, 5 + class_num, h, w)
    input_size = jnp.float32(downsample_ratio * h)

    sxy = jnp.float32(scale_x_y)
    bias = -0.5 * (sxy - 1.0)
    px = jax.nn.sigmoid(pred[:, :, 0]) * sxy + bias       # (N, an, H, W)
    py = jax.nn.sigmoid(pred[:, :, 1]) * sxy + bias
    pw = pred[:, :, 2]
    ph = pred[:, :, 3]
    pobj = pred[:, :, 4]
    pcls = pred[:, :, 5:]                                  # (N, an, K, H, W)

    gx = gt_box[..., 0]                                    # (N, B) in [0,1]
    gy = gt_box[..., 1]
    gw = jnp.maximum(gt_box[..., 2], 1e-10)
    gh = jnp.maximum(gt_box[..., 3], 1e-10)
    valid = (gw > 1e-8) & (gh > 1e-8)

    # best anchor per gt over ALL anchors by wh-IoU (reference assignment)
    aw = jnp.asarray(an_all[:, 0]) / input_size            # (A,)
    ah = jnp.asarray(an_all[:, 1]) / input_size
    inter = jnp.minimum(gw[..., None], aw) * jnp.minimum(gh[..., None], ah)
    union = gw[..., None] * gh[..., None] + aw * ah - inter
    best_anchor = jnp.argmax(inter / union, axis=-1)       # (N, B)

    gi = jnp.clip((gx * w).astype(jnp.int32), 0, w - 1)    # (N, B)
    gj = jnp.clip((gy * h).astype(jnp.int32), 0, h - 1)

    # one-hot scatter of targets onto the (an, H, W) grid
    local = jnp.searchsorted(jnp.asarray(mask_idx), best_anchor)
    in_mask = jnp.take(jnp.isin(np.arange(len(an_all)), mask_idx),
                       best_anchor) & valid               # (N, B)
    onehot = (jax.nn.one_hot(local, an, dtype=jnp.float32)[..., None, None] *
              jax.nn.one_hot(gj, h, dtype=jnp.float32)[:, :, None, :, None] *
              jax.nn.one_hot(gi, w, dtype=jnp.float32)[:, :, None, None, :])
    onehot = onehot * in_mask[..., None, None, None].astype(jnp.float32)
    obj_mask = jnp.clip(jnp.sum(onehot, axis=1), 0.0, 1.0)  # (N, an, H, W)

    def scatter(vals):  # (N, B) -> (N, an, H, W)
        return jnp.sum(onehot * vals[..., None, None, None], axis=1)

    tx = scatter(gx * w - gi.astype(jnp.float32))
    ty = scatter(gy * h - gj.astype(jnp.float32))
    sel_aw = jnp.take(jnp.asarray(an_all[:, 0]), best_anchor) / input_size
    sel_ah = jnp.take(jnp.asarray(an_all[:, 1]), best_anchor) / input_size
    tw = scatter(jnp.log(gw / sel_aw))
    th = scatter(jnp.log(gh / sel_ah))
    box_scale = scatter(2.0 - gw * gh)                     # small-box boost
    score = gt_score if gt_score is not None else jnp.ones((n, nb),
                                                           jnp.float32)
    tscore = scatter(score)

    # ignore mask: predicted boxes with IoU > thresh vs ANY gt are not
    # penalised as background
    grid_x = (jnp.arange(w, dtype=jnp.float32) + 0.0)[None, None, None, :]
    grid_y = (jnp.arange(h, dtype=jnp.float32) + 0.0)[None, None, :, None]
    sel = jnp.asarray(an_all[mask_idx])                    # (an, 2)
    bx = (px + grid_x) / w
    by = (py + grid_y) / h
    bw = jnp.exp(jnp.clip(pw, -10, 10)) * sel[None, :, 0, None, None] / \
        input_size
    bh = jnp.exp(jnp.clip(ph, -10, 10)) * sel[None, :, 1, None, None] / \
        input_size

    def iou_with_gt(bx, by, bw, bh, gx, gy, gw, gh):
        # pred (N, an, H, W) vs gt (N, B) -> (N, B, an, H, W)
        px0 = (bx - bw / 2)[:, None]
        py0 = (by - bh / 2)[:, None]
        px1 = (bx + bw / 2)[:, None]
        py1 = (by + bh / 2)[:, None]
        gx0 = (gx - gw / 2)[..., None, None, None]
        gy0 = (gy - gh / 2)[..., None, None, None]
        gx1 = (gx + gw / 2)[..., None, None, None]
        gy1 = (gy + gh / 2)[..., None, None, None]
        iw = jnp.maximum(jnp.minimum(px1, gx1) - jnp.maximum(px0, gx0), 0.0)
        ih = jnp.maximum(jnp.minimum(py1, gy1) - jnp.maximum(py0, gy0), 0.0)
        inter = iw * ih
        union = (px1 - px0) * (py1 - py0) + \
            (gx1 - gx0) * (gy1 - gy0) - inter
        return inter / jnp.maximum(union, 1e-10)

    ious = iou_with_gt(bx, by, bw, bh, gx, gy, gw, gh)
    ious = jnp.where(valid[..., None, None, None], ious, 0.0)
    ignore = (jnp.max(ious, axis=1) > ignore_thresh).astype(jnp.float32)

    def bce(logit_or_p, t, from_logits=True):
        if from_logits:
            return jnp.maximum(logit_or_p, 0) - logit_or_p * t + \
                jnp.log1p(jnp.exp(-jnp.abs(logit_or_p)))
        p = jnp.clip(logit_or_p, 1e-10, 1.0 - 1e-10)
        return -(t * jnp.log(p) + (1 - t) * jnp.log(1 - p))

    m = obj_mask * tscore * box_scale
    loss_xy = jnp.sum((bce(px, tx, from_logits=False) * m), axis=(1, 2, 3))
    loss_wh = jnp.sum((jnp.abs(pw - tw) + jnp.abs(ph - th)) * m,
                      axis=(1, 2, 3))
    loss_obj = jnp.sum(bce(pobj, obj_mask) * obj_mask * tscore +
                       bce(pobj, obj_mask) * (1 - obj_mask) * (1 - ignore),
                       axis=(1, 2, 3))
    if use_label_smooth:
        delta = 1.0 / max(class_num, 1)
        lo, hi = delta, 1.0 - delta
    else:
        lo, hi = 0.0, 1.0
    tcls_onehot = jnp.sum(
        onehot[:, :, :, None] *
        jax.nn.one_hot(gt_label, class_num,
                       dtype=jnp.float32)[:, :, None, :, None, None],
        axis=1)                                            # (N, an, K, H, W)
    tcls = tcls_onehot * hi + (1 - tcls_onehot) * lo
    loss_cls = jnp.sum(
        bce(pcls, tcls) * obj_mask[:, :, None] * tscore[:, :, None],
        axis=(1, 2, 3, 4))
    return loss_xy + loss_wh + loss_obj + loss_cls


# ---------------------------------------------------------------------------
# RoI ops
# ---------------------------------------------------------------------------

def _rois_to_batch(boxes, boxes_num, n):
    """(K,4) boxes + per-image counts -> (K,) batch indices (static K)."""
    k = boxes.shape[0]
    cum = jnp.cumsum(boxes_num)
    return jnp.sum(jnp.arange(k)[:, None] >= cum[None, :], axis=1)


def _bilinear(fm, y, x):
    """fm: (C, H, W); y/x: (...,) float coords -> (C, ...)."""
    h, w = fm.shape[-2], fm.shape[-1]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy1 = y - y0
    wx1 = x - x0
    y0i = jnp.clip(y0.astype(jnp.int32), 0, h - 1)
    y1i = jnp.clip(y0i + 1, 0, h - 1)
    x0i = jnp.clip(x0.astype(jnp.int32), 0, w - 1)
    x1i = jnp.clip(x0i + 1, 0, w - 1)
    v00 = fm[:, y0i, x0i]
    v01 = fm[:, y0i, x1i]
    v10 = fm[:, y1i, x0i]
    v11 = fm[:, y1i, x1i]
    out = (v00 * (1 - wy1) * (1 - wx1) + v01 * (1 - wy1) * wx1 +
           v10 * wy1 * (1 - wx1) + v11 * wy1 * wx1)
    # zero outside the feature map like the reference kernel
    inside = (y > -1.0) & (y < h) & (x > -1.0) & (x < w)
    return out * inside.astype(out.dtype)


@wrap_op
def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """reference: vision/ops.py roi_align:1160 (phi roi_align kernel).
    x: (N, C, H, W); boxes: (K, 4) xyxy; returns (K, C, ph, pw)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    n, c, h, w = x.shape
    k = boxes.shape[0]
    batch_idx = _rois_to_batch(boxes, boxes_num, n)
    offset = 0.5 if aligned else 0.0
    bx0 = boxes[:, 0] * spatial_scale - offset
    by0 = boxes[:, 1] * spatial_scale - offset
    bx1 = boxes[:, 2] * spatial_scale - offset
    by1 = boxes[:, 3] * spatial_scale - offset
    rw = bx1 - bx0
    rh = by1 - by0
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_h = rh / ph
    bin_w = rw / pw
    if sampling_ratio > 0:
        srm = int(sampling_ratio)
        ry = jnp.full((k,), float(srm), jnp.float32)
        rx = jnp.full((k,), float(srm), jnp.float32)
    else:
        # reference adaptive grid: ceil(bin_h) x ceil(bin_w) samples per
        # bin, per RoI (phi roi_align kernel).  XLA needs static shapes, so
        # sample a static SRM x SRM grid and MASK to the first
        # ceil(bin)<=SRM rows/cols per RoI; RoIs whose adaptive count
        # exceeds SRM are clamped (documented deviation — beyond 4x4
        # samples per bin the bilinear average has converged for typical
        # feature maps).
        srm = 4
        ry = jnp.clip(jnp.ceil(bin_h), 1.0, srm)
        rx = jnp.clip(jnp.ceil(bin_w), 1.0, srm)
    iy = jnp.arange(ph, dtype=jnp.float32)
    ix = jnp.arange(pw, dtype=jnp.float32)
    samp = jnp.arange(srm, dtype=jnp.float32)
    sy = (samp[None, :] + 0.5) / ry[:, None]                # (K, srm)
    sx = (samp[None, :] + 0.5) / rx[:, None]
    my = (samp[None, :] < ry[:, None]).astype(jnp.float32)  # (K, srm)
    mx = (samp[None, :] < rx[:, None]).astype(jnp.float32)
    # y coords: (K, ph, srm)
    yy = by0[:, None, None] + (iy[None, :, None] +
                               sy[:, None, :]) * bin_h[:, None, None]
    xx = bx0[:, None, None] + (ix[None, :, None] +
                               sx[:, None, :]) * bin_w[:, None, None]
    cnt = ry * rx                                           # (K,)

    def per_roi(bi, ys, xs, myk, mxk, cn):
        fm = x[bi]                                          # (C, H, W)
        grid_y = ys[:, :, None, None]                       # (ph, srm, 1, 1)
        grid_x = xs[None, None, :, :]                       # (1, 1, pw, srm)
        vals = _bilinear(fm, jnp.broadcast_to(
            grid_y, (ph, srm, pw, srm)), jnp.broadcast_to(
            grid_x, (ph, srm, pw, srm)))                    # (C,ph,srm,pw,srm)
        mask = myk[None, None, :, None, None] * mxk[None, None, None,
                                                    None, :]
        return (vals * mask).sum(axis=(2, 4)) / cn          # (C, ph, pw)

    return jax.vmap(per_roi)(batch_idx, yy, xx, my, mx, cnt)


@wrap_op
def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """reference: vision/ops.py roi_pool:1033 — quantized max pooling."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    n, c, h, w = x.shape
    batch_idx = _rois_to_batch(boxes, boxes_num, n)
    x0 = jnp.round(boxes[:, 0] * spatial_scale).astype(jnp.int32)
    y0 = jnp.round(boxes[:, 1] * spatial_scale).astype(jnp.int32)
    x1 = jnp.round(boxes[:, 2] * spatial_scale).astype(jnp.int32)
    y1 = jnp.round(boxes[:, 3] * spatial_scale).astype(jnp.int32)
    rh = jnp.maximum(y1 - y0 + 1, 1)
    rw = jnp.maximum(x1 - x0 + 1, 1)

    hs = jnp.arange(h)
    ws = jnp.arange(w)

    def per_roi(bi, x0r, y0r, rhr, rwr):
        fm = x[bi]                                           # (C, H, W)
        def bin_mask(i, j):
            hstart = y0r + (i * rhr) // ph
            hend = y0r + ((i + 1) * rhr + ph - 1) // ph
            wstart = x0r + (j * rwr) // pw
            wend = x0r + ((j + 1) * rwr + pw - 1) // pw
            mh = (hs >= hstart) & (hs < jnp.maximum(hend, hstart + 1))
            mw = (ws >= wstart) & (ws < jnp.maximum(wend, wstart + 1))
            m = mh[:, None] & mw[None, :]
            neg = jnp.full_like(fm, -jnp.inf)
            return jnp.max(jnp.where(m[None], fm, neg), axis=(1, 2))
        rows = [jnp.stack([bin_mask(i, j) for j in range(pw)], axis=-1)
                for i in range(ph)]
        out = jnp.stack(rows, axis=-2)                       # (C, ph, pw)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(per_roi)(batch_idx, x0, y0, rh, rw)


@wrap_op
def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """reference: vision/ops.py psroi_pool:918 — position-sensitive average
    pooling: input channels C = out_c * ph * pw; bin (i, j) of output
    channel k averages input channel k*ph*pw + i*pw + j."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    n, c, h, w = x.shape
    if c % (ph * pw):
        raise ValueError(
            f"psroi_pool: input channels {c} must be a multiple of "
            f"output_size {ph}x{pw}")
    out_c = c // (ph * pw)
    batch_idx = _rois_to_batch(boxes, boxes_num, n)
    bx0 = boxes[:, 0] * spatial_scale
    by0 = boxes[:, 1] * spatial_scale
    bx1 = boxes[:, 2] * spatial_scale
    by1 = boxes[:, 3] * spatial_scale
    rh = jnp.maximum(by1 - by0, 0.1)
    rw = jnp.maximum(bx1 - bx0, 0.1)
    hs = jnp.arange(h, dtype=jnp.float32)
    ws = jnp.arange(w, dtype=jnp.float32)

    def per_roi(bi, x0r, y0r, rhr, rwr):
        fm = x[bi].reshape(out_c, ph, pw, h, w)
        bin_h = rhr / ph
        bin_w = rwr / pw
        i = jnp.arange(ph, dtype=jnp.float32)
        j = jnp.arange(pw, dtype=jnp.float32)
        hstart = jnp.floor(y0r + i * bin_h)[:, None]          # (ph, 1)
        hend = jnp.ceil(y0r + (i + 1) * bin_h)[:, None]
        wstart = jnp.floor(x0r + j * bin_w)[:, None]          # (pw, 1)
        wend = jnp.ceil(x0r + (j + 1) * bin_w)[:, None]
        mh = (hs[None] >= hstart) & (hs[None] < hend)         # (ph, H)
        mw = (ws[None] >= wstart) & (ws[None] < wend)         # (pw, W)
        m = (mh[:, None, :, None] & mw[None, :, None, :]).astype(jnp.float32)
        sums = jnp.einsum("cijhw,ijhw->cij", fm, m)
        counts = jnp.maximum(jnp.sum(m, axis=(2, 3)), 1.0)
        return sums / counts

    return jax.vmap(per_roi)(batch_idx, bx0, by0, rh, rw)


@wrap_op
def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy non-maximum suppression (reference: vision/ops.py nms:1376).

    boxes: (K, 4) xyxy.  Returns kept indices sorted by score.  With
    ``category_idxs``, suppression is per category (boxes of different
    categories never suppress each other).  The IoU matrix + keep scan are
    static-shape lax; the final index compaction is data-dependent, so this
    op is EAGER-only (inside jit, compute the boolean keep mask yourself
    and mask downstream instead of compacting).
    """
    k = boxes.shape[0]
    if scores is None:
        scores = jnp.arange(k, 0, -1, dtype=jnp.float32)  # input order
    order = jnp.argsort(-scores)
    b = boxes[order]
    x0, y0, x1, y1 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    area = jnp.maximum(x1 - x0, 0) * jnp.maximum(y1 - y0, 0)
    ix0 = jnp.maximum(x0[:, None], x0[None, :])
    iy0 = jnp.maximum(y0[:, None], y0[None, :])
    ix1 = jnp.minimum(x1[:, None], x1[None, :])
    iy1 = jnp.minimum(y1[:, None], y1[None, :])
    inter = jnp.maximum(ix1 - ix0, 0) * jnp.maximum(iy1 - iy0, 0)
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-10)
    if category_idxs is not None:
        cats = category_idxs[order]
        same = cats[:, None] == cats[None, :]
        iou = jnp.where(same, iou, 0.0)

    over = iou > iou_threshold

    def step(keep, i):
        # keep box i iff no higher-scored KEPT box overlaps it
        sup = jnp.any(keep & over[i] & (jnp.arange(k) < i))
        keep = keep.at[i].set(~sup)
        return keep, None

    keep, _ = jax.lax.scan(step, jnp.zeros((k,), bool), jnp.arange(k))
    kept = order[keep]   # original indices of survivors, in score order
    if top_k is not None:
        kept = kept[:top_k]
    return kept


# ---------------------------------------------------------------------------
# deformable convolution
# ---------------------------------------------------------------------------

@wrap_op
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Deformable convolution v1/v2 (reference: vision/ops.py
    deform_conv2d:430, operators/deformable_conv_op.*): each kernel tap
    samples the input at a learned offset (bilinear), v2 additionally
    modulates with ``mask``.  im2col-with-offsets + one einsum.
    """
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    n, c, h, w = x.shape
    out_c, in_c_g, kh, kw = weight.shape
    sh, sw = stride
    ph_, pw_ = padding
    dh, dw = dilation
    out_h = (h + 2 * ph_ - dh * (kh - 1) - 1) // sh + 1
    out_w = (w + 2 * pw_ - dw * (kw - 1) - 1) // sw + 1
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError(
            "TPU build: deform_conv2d supports groups=1, "
            "deformable_groups=1 (the common configuration)")

    # base sampling locations per output position and tap
    oy = jnp.arange(out_h, dtype=jnp.float32) * sh - ph_
    ox = jnp.arange(out_w, dtype=jnp.float32) * sw - pw_
    ky = jnp.arange(kh, dtype=jnp.float32) * dh
    kx = jnp.arange(kw, dtype=jnp.float32) * dw
    base_y = oy[:, None, None, None] + ky[None, None, :, None]  # (OH,1,KH,1)
    base_x = ox[None, :, None, None] + kx[None, None, None, :]  # (1,OW,1,KW)
    off = offset.reshape(n, kh * kw, 2, out_h, out_w)
    off_y = off[:, :, 0].reshape(n, kh, kw, out_h, out_w)
    off_x = off[:, :, 1].reshape(n, kh, kw, out_h, out_w)
    sample_y = base_y[None] + jnp.moveaxis(off_y, (1, 2), (3, 4)) \
        .reshape(n, out_h, out_w, kh, kw)
    sample_x = base_x[None] + jnp.moveaxis(off_x, (1, 2), (3, 4)) \
        .reshape(n, out_h, out_w, kh, kw)

    def per_image(fm, ys, xs):
        return _bilinear(fm, ys, xs)        # (C, OH, OW, KH, KW)

    cols = jax.vmap(per_image)(x, sample_y, sample_x)
    if mask is not None:
        m = mask.reshape(n, kh, kw, out_h, out_w)
        cols = cols * jnp.moveaxis(m, (1, 2), (3, 4))[:, None]
    out = jnp.einsum("nchwkl,ockl->nohw", cols, weight)
    if bias is not None:
        out = out + bias[None, :, None, None]
    return out


class DeformConv2D(nn.Layer):
    """reference: vision/ops.py DeformConv2D:633."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.deformable_groups = deformable_groups
        self.groups = groups
        from ..nn import initializer as I
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + tuple(kernel_size),
            default_initializer=I.XavierNormal())
        if bias_attr is not False:
            self.bias = self.create_parameter((out_channels,), is_bias=True)
        else:
            self.bias = None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=self.stride, padding=self.padding,
                             dilation=self.dilation,
                             deformable_groups=self.deformable_groups,
                             groups=self.groups, mask=mask)


# ---------------------------------------------------------------------------
# layer helpers + IO
# ---------------------------------------------------------------------------

class RoIAlign(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


class RoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


class ConvNormActivation(nn.Sequential):
    """reference: vision/ops.py ConvNormActivation:1322."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, norm_layer=nn.BatchNorm2D,
                 activation_layer=nn.ReLU, dilation=1, bias=None):
        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if bias is None:
            bias = norm_layer is None
        layers = [nn.Conv2D(in_channels, out_channels, kernel_size,
                            stride=stride, padding=padding,
                            dilation=dilation, groups=groups,
                            bias_attr=None if bias else False)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)


def read_file(filename, name=None):
    """reference: vision/ops.py read_file:826 — file bytes as a uint8
    tensor."""
    from ..core.tensor import Tensor
    data = np.fromfile(filename, dtype=np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """reference: vision/ops.py decode_jpeg:871.  Host-side decode via
    PIL (no nvjpeg on TPU); returns (C, H, W) uint8."""
    try:
        from PIL import Image
    except ImportError:
        raise NotImplementedError(
            "decode_jpeg needs PIL, which is not available in this build")
    import io as _io
    from ..core.tensor import Tensor
    arr = np.asarray(x.numpy() if hasattr(x, "numpy") else x, np.uint8)
    img = Image.open(_io.BytesIO(arr.tobytes()))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    out = np.asarray(img)
    if out.ndim == 2:
        out = out[None, :, :]
    else:
        out = out.transpose(2, 0, 1)
    return Tensor(jnp.asarray(out))
