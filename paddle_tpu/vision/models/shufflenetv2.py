"""ShuffleNetV2 (reference parity: python/paddle/vision/models/shufflenetv2.py
— channel split + shuffle, Ma et al. 2018)."""
from __future__ import annotations

from ... import nn, ops


def _channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = ops.reshape(x, [b, groups, c // groups, h, w])
    x = ops.transpose(x, [0, 2, 1, 3, 4])
    return ops.reshape(x, [b, c, h, w])


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, act_layer=nn.ReLU):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act_layer())
            in2 = in_c
        else:
            self.branch1 = None
            in2 = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(in2, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act_layer(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act_layer())

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = ops.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = ops.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    _stage_out = {
        0.25: (24, 24, 48, 96, 512),
        0.33: (24, 32, 64, 128, 512),
        0.5: (24, 48, 96, 192, 1024),
        1.0: (24, 116, 232, 464, 1024),
        1.5: (24, 176, 352, 704, 1024),
        2.0: (24, 244, 488, 976, 2048),
    }
    _repeats = (4, 8, 4)

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        if scale not in self._stage_out:
            raise ValueError(f"supported scales: {sorted(self._stage_out)}")
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        chans = self._stage_out[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, chans[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(chans[0]), act_layer())
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = chans[0]
        for out_c, reps in zip(chans[1:4], self._repeats):
            stages.append(_InvertedResidual(in_c, out_c, 2, act_layer))
            for _ in range(reps - 1):
                stages.append(_InvertedResidual(out_c, out_c, 1, act_layer))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_c, chans[4], 1, bias_attr=False),
            nn.BatchNorm2D(chans[4]), act_layer())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chans[4], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def _make(scale, act="relu", pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled in the TPU build")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _make(0.25, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _make(0.33, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _make(0.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _make(1.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _make(1.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _make(2.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _make(1.0, act="swish", pretrained=pretrained, **kwargs)
