"""DenseNet (reference parity: python/paddle/vision/models/densenet.py —
densely connected blocks, Huang et al. 2017).  jnp-native rewrite: dense
connectivity via channel concat; bottleneck 1x1 -> 3x3 layers."""
from __future__ import annotations

from ... import nn, ops


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return ops.concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    """layers in {121, 161, 169, 201, 264} (reference densenet.py)."""

    _cfgs = {
        121: (64, 32, (6, 12, 24, 16)),
        161: (96, 48, (6, 12, 36, 24)),
        169: (64, 32, (6, 12, 32, 32)),
        201: (64, 32, (6, 12, 48, 32)),
        264: (64, 32, (6, 12, 64, 48)),
    }

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        if layers not in self._cfgs:
            raise ValueError(f"supported layers: {sorted(self._cfgs)}, "
                             f"got {layers}")
        num_init, growth, block_cfg = self._cfgs[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        c = num_init
        features = []
        for bi, n_layers in enumerate(block_cfg):
            for _ in range(n_layers):
                features.append(_DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if bi != len(block_cfg) - 1:
                features.append(_Transition(c, c // 2))
                c //= 2
        features.append(nn.BatchNorm2D(c))
        features.append(nn.ReLU())
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.features(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(ops.flatten(x, 1))
        return x


def _densenet(layers, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled in the TPU build")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
