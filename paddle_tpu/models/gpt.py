"""GPT-2 — the flagship language model (reference capability target:
BASELINE.md config 4, "GPT-2 345M ... fused attention/FFN"; the reference's
closest in-tree models are fleet's GPT test models,
python/paddle/fluid/tests/unittests/auto_parallel_gpt_model.py).

TPU-first design:
* pre-LN transformer, bf16-friendly, weight-tied logits
* attention via F.scaled_dot_product_attention -> Pallas flash kernel
* Megatron sharding ANNOTATIONS baked into the parameters (pspec): qkv/fc1
  column-sharded on 'mp', out-proj/fc2 row-sharded, embeddings vocab-sharded;
  activations constrained to ('dp', 'sep', None) so sequence parallelism
  shards the token axis.  Under pjit these annotations are the whole
  distribution strategy (GSPMD inserts the collectives the reference's
  mp_layers/c_* ops hand-coded).
* vocab padded to a multiple of 128 so the logits matmul tiles the MXU.
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from .. import ops
from ..core.dispatch import call
from ..core.tensor import Tensor
from ..distributed import mp_overlap as _mpo
from ..distributed.mp_layers import shard_heads, with_sharding_constraint
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.layers import Layer, LayerList
from ..nn.layer.norm import LayerNorm


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304          # 50257 padded to 128-multiple (MXU tiling)
    max_position_embeddings: int = 1024
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: int = 4096
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True
    # activation recompute per block (jax.checkpoint): trades ~1/3 more
    # FLOPs for O(sqrt)-ish activation memory — required for long-sequence
    # training (s=8192 without it sits at the 16GB HBM edge on one v5e)
    use_recompute: bool = False
    # scan_layers: hold the L identical blocks as NATIVELY stacked (L, ...)
    # parameter arrays and run lax.scan over the layer axis.  Grads arrive
    # stacked BY CONSTRUCTION (scan's transpose accumulates them — no
    # per-name<->stacked bridge, the thing that sank both prior layout
    # experiments, PERF.md rounds 3-4), so the optimizer update is ~17 big
    # fusions at large-array HBM bandwidth instead of ~300 small ones.
    scan_layers: bool = False
    # unroll factor for the layer scan.  unroll=num_hidden_layers gives
    # straight-line HLO (XLA fuses/remats across layer boundaries exactly
    # like the per-layer model — a rolled scan stacks every backward
    # residual as (L, ...) loop buffers, measured 17.4G HBM = OOM on one
    # v5e at the 345M bench shapes) while keeping the stacked param layout.
    scan_unroll: int = 1
    # how the stacked params meet the per-layer compute:
    #   "scan"      — lax.scan (with scan_unroll); grads accumulate via
    #                 per-layer dynamic-update-slice (measured 18.3 ms/step
    #                 of bitcast+DUS fusions at the 345M bench)
    #   "stack_vjp" — python loop over custom_vjp slice views whose
    #                 backward builds each stacked grad with ONE jnp.stack
    #                 (the exact cotangent for disjoint static slices —
    #                 same trick as TrainStep._make_flat_unflatten)
    scan_mode: str = "scan"

    @classmethod
    def gpt2_small(cls):
        return cls(hidden_size=768, num_hidden_layers=12,
                   num_attention_heads=12, intermediate_size=3072)

    @classmethod
    def gpt2_medium(cls):  # the 345M benchmark config
        return cls(hidden_size=1024, num_hidden_layers=24,
                   num_attention_heads=16, intermediate_size=4096)

    @classmethod
    def gpt2_large(cls):
        return cls(hidden_size=1280, num_hidden_layers=36,
                   num_attention_heads=20, intermediate_size=5120)

    @classmethod
    def tiny(cls):  # for tests
        return cls(vocab_size=512, max_position_embeddings=128,
                   hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=128,
                   hidden_dropout_prob=0.0, attention_dropout_prob=0.0)


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_attention_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        self.hidden_size = c.hidden_size
        init = I.Normal(0.0, c.initializer_range)
        out_init = I.Normal(0.0, c.initializer_range
                            / math.sqrt(2 * c.num_hidden_layers))
        self.qkv_proj = Linear(c.hidden_size, 3 * c.hidden_size)
        self.qkv_proj.weight.set_value(Tensor(init((c.hidden_size,
                                                    3 * c.hidden_size))))
        self.out_proj = Linear(c.hidden_size, c.hidden_size)
        self.out_proj.weight.set_value(Tensor(out_init((c.hidden_size,
                                                        c.hidden_size))))
        self.attn_dropout_p = c.attention_dropout_prob
        self.resid_dropout = Dropout(c.hidden_dropout_prob)
        # Megatron layout: qkv column-sharded, out row-sharded
        self.qkv_proj.weight.pspec = PartitionSpec(None, "mp")
        self.qkv_proj.bias.pspec = PartitionSpec("mp")
        self.out_proj.weight.pspec = PartitionSpec("mp", None)

    def _out_projection(self, out):
        # row-sharded projection: overlapped ⇒ the matmul→all-reduce runs
        # as the ring (partial-accumulate + chunked permute) island; off
        # ⇒ today's GSPMD lowering through the Linear
        if _mpo.row_viable(self.hidden_size):
            return call(
                lambda o, w, bb: _mpo.row_parallel_matmul(o, w, bb),
                out, self.out_proj.weight, self.out_proj.bias,
                name="mp_overlap_row")
        return self.out_proj(out)

    def forward(self, x, cache=None):
        b, s, _ = x.shape
        h = self.hidden_size
        static_cache = (cache is not None
                        and not isinstance(cache, (tuple, list)))
        if static_cache and _mpo.qkv_viable(self.num_heads, self.head_dim):
            # overlapped fused-qkv: column projection + 3-ppermute head
            # re-deal in one island — replaces GSPMD's per-layer
            # all-to-all/all-gather reshard from the 3H/tp shard
            # boundary to the head boundary (PR 11's named follow-up)
            nh, hd = self.num_heads, self.head_dim
            q, k, v = call(
                lambda xr, w, bb: _mpo.qkv_heads(xr, w, bb, nh, hd),
                x, self.qkv_proj.weight, self.qkv_proj.bias,
                name="mp_overlap_qkv")
        else:
            qkv = self.qkv_proj(x)
            # q/k/v as contiguous LAST-DIM slices of the fused projection:
            # reshape-to-(b,s,3,h,d)+unbind forces a transposed-layout copy
            # of the whole qkv activation per layer (~0.1 ms × 24 layers ×
            # fwd+bwd on the 345M bench); last-dim slices are free
            q = ops.reshape(qkv[:, :, :h],
                            [b, s, self.num_heads, self.head_dim])
            k = ops.reshape(qkv[:, :, h:2 * h],
                            [b, s, self.num_heads, self.head_dim])
            v = ops.reshape(qkv[:, :, 2 * h:],
                            [b, s, self.num_heads, self.head_dim])
        if static_cache:
            # static slotted cache (serving.cache view): append into the
            # preallocated buffers + length-masked attention — one shape
            # for the life of the process, no per-token retrace.  Under a
            # tensor-parallel serving mesh the q/k/v activations are
            # pinned head-sharded so the cached attention (and the pool
            # scatter) stays device-local (no-op without an 'mp' mesh)
            q, k, v = shard_heads(q), shard_heads(k), shard_heads(v)
            out = cache.attend(q, k, v)
            out = ops.reshape(out, [b, s, self.hidden_size])
            return self.resid_dropout(self._out_projection(out)), cache
        if cache is not None:
            # LEGACY CONCAT SHIM (see GPTForCausalLM.gen_legacy_concat_cache)
            pk, pv = cache
            k = ops.concat([pk, k], axis=1)
            v = ops.concat([pv, v], axis=1)
            cache = (k, v)
        # always causal: the reference SDPA mask is end-aligned
        # (tril offset sk-sq), which is exactly right for cached decode —
        # each new token sees the full past plus itself, never its future
        out = F.scaled_dot_product_attention(
            q, k, v, dropout_p=self.attn_dropout_p, is_causal=True,
            training=self.training)
        out = ops.reshape(out, [b, s, self.hidden_size])
        out = self.resid_dropout(self._out_projection(out))
        if cache is not None:
            return out, cache
        return out


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        init = I.Normal(0.0, c.initializer_range)
        out_init = I.Normal(0.0, c.initializer_range
                            / math.sqrt(2 * c.num_hidden_layers))
        self.fc1 = Linear(c.hidden_size, c.intermediate_size)
        self.fc1.weight.set_value(Tensor(init((c.hidden_size,
                                               c.intermediate_size))))
        self.fc2 = Linear(c.intermediate_size, c.hidden_size)
        self.fc2.weight.set_value(Tensor(out_init((c.intermediate_size,
                                                   c.hidden_size))))
        self.dropout = Dropout(c.hidden_dropout_prob)
        self.fc1.weight.pspec = PartitionSpec(None, "mp")
        self.fc1.bias.pspec = PartitionSpec("mp")
        self.fc2.weight.pspec = PartitionSpec("mp", None)

    def forward(self, x):
        a = F.gelu(self.fc1(x), approximate=True)
        if _mpo.row_viable(self.fc2.weight.shape[0]):
            # overlapped row matmul (ring in fwd, shard-local bwd via the
            # custom_vjp); off ⇒ GSPMD's monolithic all-reduce
            out = call(
                lambda o, w, bb: _mpo.row_parallel_matmul(o, w, bb),
                a, self.fc2.weight, self.fc2.bias, name="mp_overlap_row")
        else:
            out = self.fc2(a)
        return self.dropout(out)


class GPTBlock(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln1 = LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln2 = LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)

    def forward(self, x, cache=None):
        if cache is not None:
            a, cache = self.attn(self.ln1(x), cache)
            x = x + a
        else:
            x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        # sequence-parallel activation layout: tokens sharded over 'sep'
        x = with_sharding_constraint(x, PartitionSpec("dp", "sep", None))
        if cache is not None:
            return x, cache
        return x


#: stacked-param field -> per-layer submodule path (state_dict key mapping)
_SCAN_FIELD_MAP = {
    "ln1_w": "ln1.weight", "ln1_b": "ln1.bias",
    "qkv_w": "attn.qkv_proj.weight", "qkv_b": "attn.qkv_proj.bias",
    "out_w": "attn.out_proj.weight", "out_b": "attn.out_proj.bias",
    "ln2_w": "ln2.weight", "ln2_b": "ln2.bias",
    "fc1_w": "mlp.fc1.weight", "fc1_b": "mlp.fc1.bias",
    "fc2_w": "mlp.fc2.weight", "fc2_b": "mlp.fc2.bias",
}


def _scan_block_apply(x, p, cfg, *, training, keys=None, cache=None):
    """One transformer block over raw arrays with per-layer params ``p``
    (each a slice of the stacked (L, ...) arrays).  Matches GPTBlock's
    math exactly (pre-LN, f32 LN stats, bf16 residual stream)."""
    from ..nn.functional.attention import (scaled_dot_product_attention,
                                           sdpa_reference_raw)
    from ..nn.functional.norm import layer_norm_raw

    h_sz = cfg.hidden_size
    nh = cfg.num_attention_heads
    hd = h_sz // nh
    b, s = x.shape[0], x.shape[1]

    def dropout(a, p_drop, key):
        if p_drop <= 0.0 or not training or key is None:
            return a
        keep = jax.random.bernoulli(key, 1.0 - p_drop, a.shape)
        return jnp.where(keep, a / jnp.asarray(1.0 - p_drop, a.dtype),
                         jnp.zeros((), a.dtype))

    h = layer_norm_raw(x, p["ln1_w"], p["ln1_b"], (h_sz,),
                       cfg.layer_norm_epsilon)
    static_cache = (cache is not None
                    and not isinstance(cache, (tuple, list)))
    if static_cache and _mpo.qkv_viable(nh, hd):
        # overlapped fused-qkv island (see GPTAttention.forward)
        q, k, v = _mpo.qkv_heads(h, p["qkv_w"], p["qkv_b"], nh, hd)
    else:
        qkv = h @ p["qkv_w"] + p["qkv_b"]
        # last-dim slices (free) — see GPTAttention.forward for the
        # measured why
        q = qkv[..., :h_sz].reshape(b, s, nh, hd)
        k = qkv[..., h_sz:2 * h_sz].reshape(b, s, nh, hd)
        v = qkv[..., 2 * h_sz:].reshape(b, s, nh, hd)
    if static_cache:
        # static slotted cache view (serving.cache): in-place append +
        # length-masked attention — no shape growth, no retrace.  Head-
        # sharded under a tensor-parallel serving mesh (see
        # GPTAttention.forward; no-op without an 'mp' mesh)
        q, k, v = shard_heads(q), shard_heads(k), shard_heads(v)
        out = cache.attend_raw(q, k, v)
    elif cache is not None:
        # LEGACY CONCAT SHIM (see GPTForCausalLM.gen_legacy_concat_cache)
        pk, pv = cache
        k = jnp.concatenate([pk, k], axis=1)
        v = jnp.concatenate([pv, v], axis=1)
        cache = (k, v)
        out = scaled_dot_product_attention(q, k, v, is_causal=True,
                                           training=training)
        if isinstance(out, Tensor):
            out = out._array
    else:
        attn_p = cfg.attention_dropout_prob
        if attn_p > 0.0 and training and keys is not None:
            # explicit per-layer key: sdpa's own next_key() would be a
            # closure constant inside the scan body (same mask every layer)
            out = sdpa_reference_raw(q, k, v, None, attn_p, True, None,
                                     keys[0])
        else:
            out = scaled_dot_product_attention(q, k, v, is_causal=True,
                                               training=training)
            if isinstance(out, Tensor):
                out = out._array
    out = out.reshape(b, s, h_sz)
    if _mpo.row_viable(h_sz):
        out = _mpo.row_parallel_matmul(out, p["out_w"], p["out_b"])
    else:
        out = out @ p["out_w"] + p["out_b"]
    out = dropout(out, cfg.hidden_dropout_prob,
                  None if keys is None else keys[1])
    x = x + out
    h2 = layer_norm_raw(x, p["ln2_w"], p["ln2_b"], (h_sz,),
                        cfg.layer_norm_epsilon)
    m = jax.nn.gelu(h2 @ p["fc1_w"] + p["fc1_b"], approximate=True)
    if _mpo.row_viable(cfg.intermediate_size):
        m = _mpo.row_parallel_matmul(m, p["fc2_w"], p["fc2_b"])
    else:
        m = m @ p["fc2_w"] + p["fc2_b"]
    m = dropout(m, cfg.hidden_dropout_prob,
                None if keys is None else keys[2])
    x = x + m
    x = with_sharding_constraint(x, PartitionSpec("dp", "sep", None))
    return x, cache


class GPTScanBlocks(Layer):
    """The L transformer blocks as twelve natively stacked (L, ...)
    parameters; forward is ``lax.scan`` over the layer axis.

    This is the canonical TPU-native deep-transformer layout (the pattern
    flax's ``nn.scan`` production models use): the stacked arrays slice
    along the LEADING axis inside the loop (contiguous, no retiling — the
    (8,128) tiling lives in the trailing dims), scan's transpose
    accumulates each layer's grad into the stacked buffer in-place, and
    the optimizer sees ~12 large arrays.  Compile time also drops: the
    block body is traced/compiled once, not L times.

    Reference analogue: none (the reference materialises every layer);
    capability parity is with its fleet GPT models
    (auto_parallel_gpt_model.py) via GPTModel(scan_layers=True).
    """

    #: amp.decorate(level='O2') keeps these f32 (reference
    #: keep_batch_norm_fp32 semantics — LN params stay master precision)
    _amp_keep_fp32_params = ("ln1_w", "ln1_b", "ln2_w", "ln2_b")

    def __init__(self, config: GPTConfig):
        super().__init__()
        from ..core.tensor import Parameter
        c = config
        self.config = c
        L, H, Iz = c.num_hidden_layers, c.hidden_size, c.intermediate_size
        std = c.initializer_range
        out_std = std / math.sqrt(2 * L)

        def param(shape, init, pspec=None):
            p = Parameter(Tensor(init(tuple(shape)))._array)
            if pspec is not None:
                p.pspec = pspec
            return p

        P = PartitionSpec
        normal, out_normal = I.Normal(0.0, std), I.Normal(0.0, out_std)
        ones, zeros = I.Constant(1.0), I.Constant(0.0)
        self.ln1_w = param((L, H), ones)
        self.ln1_b = param((L, H), zeros)
        self.qkv_w = param((L, H, 3 * H), normal, P(None, None, "mp"))
        self.qkv_b = param((L, 3 * H), zeros, P(None, "mp"))
        self.out_w = param((L, H, H), out_normal, P(None, "mp", None))
        self.out_b = param((L, H), zeros)
        self.ln2_w = param((L, H), ones)
        self.ln2_b = param((L, H), zeros)
        self.fc1_w = param((L, H, Iz), normal, P(None, None, "mp"))
        self.fc1_b = param((L, Iz), zeros, P(None, "mp"))
        self.fc2_w = param((L, Iz, H), out_normal, P(None, "mp", None))
        self.fc2_b = param((L, H), zeros)

    def forward(self, x, cache=None):
        from ..core.dispatch import call
        c = self.config
        params = {n: self._parameters[n] for n in _SCAN_FIELD_MAP}
        keys = None
        any_drop = (c.hidden_dropout_prob > 0.0
                    or c.attention_dropout_prob > 0.0)
        if self.training and any_drop and cache is None:
            from ..core import random as _rnd
            flat = jax.random.split(_rnd.next_key(), c.num_hidden_layers * 3)
            keys = flat.reshape(c.num_hidden_layers, 3, *flat.shape[1:])
        if cache is not None and not isinstance(cache, (tuple, list)):
            # slotted/paged decode path: the per-layer walk re-enters
            # inside ONE traced fn, over a clone of the view whose arrays
            # are that trace's own arguments (and outputs — no tracer
            # leaks onto the caller's view object).  The view declares
            # which arrays it threads (carry_arrays: k/v, the int8 scale
            # pools when quantized, the page table for the paged layout,
            # lengths, and the opt-in quant-error scalar) and which come
            # back mutated (k, v, scales, quant_err).
            seq = int(x.shape[1]) if hasattr(x, "shape") else 1
            carries = cache.carry_arrays()

            def raw_decode_cached(x, params, *arrs):
                inner = cache.clone_raw(*arrs)
                for i in range(c.num_hidden_layers):
                    pi = {k: v[i] for k, v in params.items()}
                    x, _ = _scan_block_apply(x, pi, c, training=False,
                                             cache=inner)
                return (x,) + tuple(inner.mutated_arrays())

            out = call(raw_decode_cached, x, params, *carries,
                       name="gpt_scan_blocks")
            cache.adopt(*out[1:], steps=seq)
            return out[0], cache
        if cache is not None:
            # LEGACY CONCAT SHIM decode path: python loop over leading-axis
            # slices (no grads); shapes grow per token — retraces every step
            def raw_decode(x, params, *flat_cache):
                cache_l = [(flat_cache[2 * i], flat_cache[2 * i + 1])
                           for i in range(c.num_hidden_layers)]
                new_caches = []
                for i in range(c.num_hidden_layers):
                    pi = {k: v[i] for k, v in params.items()}
                    x, ci = _scan_block_apply(x, pi, c, training=False,
                                              cache=cache_l[i])
                    new_caches.append(ci)
                return (x,) + tuple(a for kv in new_caches for a in kv)
            flat_cache = [a for kv in cache for a in kv]
            out = call(raw_decode, x, params, *flat_cache,
                       name="gpt_scan_blocks")
            x_out = out[0]
            new_caches = [(out[1 + 2 * i], out[2 + 2 * i])
                          for i in range(c.num_hidden_layers)]
            return x_out, new_caches

        training = self.training

        def raw_scan(x, params, keys):
            def body(carry, xs):
                pi, ki = xs
                y, _ = _scan_block_apply(carry, pi, c, training=training,
                                         keys=ki)
                return y, None
            if c.use_recompute and training:
                body = jax.checkpoint(body)
            xs = (params, keys)
            unroll = max(1, min(int(c.scan_unroll), c.num_hidden_layers))
            y, _ = jax.lax.scan(body, x, xs, unroll=unroll)
            return y

        def raw_stack_vjp(x, params, keys):
            L = c.num_hidden_layers
            views = _unstack_for_grad(params, L)

            def block(x, pi, ki):
                return _scan_block_apply(x, pi, c, training=training,
                                         keys=ki)[0]
            if c.use_recompute and training:
                block = jax.checkpoint(block)
            for i in range(L):
                x = block(x, views[i],
                          None if keys is None else keys[i])
            return x

        raw = raw_stack_vjp if c.scan_mode == "stack_vjp" else raw_scan
        return call(raw, x, params, keys, name="gpt_scan_blocks")


def _unstack_for_grad(params, L):
    """Slice {name: (L, ...)} stacked params into L per-layer dicts through
    a custom_vjp whose backward is ONE jnp.stack per stacked array — the
    exact cotangent for disjoint static slices, avoiding both jax's
    pad-and-add slice transpose (round-3 stacked experiment) and scan's
    per-layer dynamic-update-slice accumulation (18.3 ms/step measured,
    PERF.md round 5)."""
    @jax.custom_vjp
    def unstack(stacked):
        return tuple({k: v[i] for k, v in stacked.items()}
                     for i in range(L))

    def fwd(stacked):
        return unstack(stacked), None

    def bwd(_, cots):
        return ({k: jnp.stack([c[k] for c in cots]) for k in cots[0]},)

    unstack.defvjp(fwd, bwd)
    return unstack(params)


def scan_state_to_per_layer(state):
    """Host-side checkpoint mapping: a scan-layers model's stacked state
    ('gpt.h_stack.qkv_w': (L, H, 3H)) -> per-layer names
    ('gpt.h.{i}.attn.qkv_proj.weight').  Checkpoints stay per-name
    portable regardless of the in-memory layout."""
    out = {}
    for k, v in state.items():
        if ".h_stack." in k:
            prefix, field = k.rsplit(".h_stack.", 1)
            sub = _SCAN_FIELD_MAP[field]
            for i in range(int(v.shape[0])):
                out["%s.h.%d.%s" % (prefix, i, sub)] = v[i]
        else:
            out[k] = v
    return out


def per_layer_state_to_scan(state):
    """Inverse of :func:`scan_state_to_per_layer`: stack per-layer entries
    into the scan model's (L, ...) arrays.  Non-block entries pass through."""
    import re
    pat = re.compile(r"^(.*)\.h\.(\d+)\.(.+)$")
    rev = {v: k for k, v in _SCAN_FIELD_MAP.items()}
    out, groups = {}, {}
    for k, v in state.items():
        m = pat.match(k)
        if m and m.group(3) in rev:
            key = (m.group(1), rev[m.group(3)])
            groups.setdefault(key, {})[int(m.group(2))] = v
        else:
            out[k] = v
    for (prefix, field), per in groups.items():
        idxs = sorted(per)
        if idxs != list(range(len(idxs))):
            raise ValueError("per-layer state has gaps for %s.h.*.%s: %r"
                             % (prefix, _SCAN_FIELD_MAP[field], idxs))
        out["%s.h_stack.%s" % (prefix, field)] = jnp.stack(
            [jnp.asarray(per[i]) for i in idxs])
    return out


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        c = config
        init = I.Normal(0.0, c.initializer_range)
        self.wte = Embedding(c.vocab_size, c.hidden_size)
        self.wte.weight.set_value(Tensor(init((c.vocab_size, c.hidden_size))))
        self.wte.weight.pspec = PartitionSpec("mp", None)   # vocab-parallel
        self.wpe = Embedding(c.max_position_embeddings, c.hidden_size)
        self.wpe.weight.set_value(
            Tensor(init((c.max_position_embeddings, c.hidden_size))))
        self.drop = Dropout(c.hidden_dropout_prob)
        if c.scan_layers:
            self.h_stack = GPTScanBlocks(c)
        else:
            self.h = LayerList(
                [GPTBlock(c) for _ in range(c.num_hidden_layers)])
        self.ln_f = LayerNorm(c.hidden_size, c.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None, cache=None):
        b, s = input_ids.shape
        finalize = False
        view = None
        if cache is not None and not isinstance(cache, (tuple, list)):
            from ..serving.cache import (DecodeView, PagedDecodeView,
                                         PagedKVCache, SlottedKVCache,
                                         is_cache_view)
            if isinstance(cache, SlottedKVCache):
                # bare cache state -> batched decode semantics; the caller
                # gets the advanced SlottedKVCache back
                cache = DecodeView(cache)
                finalize = True
            elif isinstance(cache, PagedKVCache):
                cache = PagedDecodeView(cache)
                finalize = True
            if not is_cache_view(cache):
                raise TypeError(
                    "cache must be a SlottedKVCache, a PagedKVCache, a "
                    "serving cache view, or the legacy per-layer (k, v) "
                    "tuple list; got %r" % (type(cache).__name__,))
            view = cache
        if position_ids is None:
            if view is not None:
                position_ids = Tensor(view.position_ids(b, s))
            else:
                start = 0 if cache is None else cache[0][0].shape[1]
                position_ids = ops.arange(start, start + s, dtype="int32")
                position_ids = ops.unsqueeze(position_ids, 0)
        if _mpo.embed_viable(self.config.vocab_size):
            # overlapped vocab-parallel lookup: masked local gather +
            # psum (activation-sized all-reduce) instead of GSPMD's
            # table-sized all-gather
            tok = call(lambda ids, w: _mpo.vocab_embed(ids, w),
                       input_ids, self.wte.weight, name="mp_overlap_embed")
            x = tok + self.wpe(position_ids)
        else:
            x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        x = with_sharding_constraint(x, PartitionSpec("dp", "sep", None))
        if self.config.scan_layers:
            if cache is not None:
                x, new_caches = self.h_stack(x, cache)
                if finalize:
                    new_caches = view.finalize()
                return self.ln_f(x), new_caches
            return self.ln_f(self.h_stack(x))
        new_caches = []
        if self.config.use_recompute and self.training and cache is None:
            from ..distributed.recompute import recompute as _recompute
        else:
            _recompute = None
        for i, block in enumerate(self.h):
            if view is not None:
                x, _ = block(x, view)
            elif cache is not None:
                x, ci = block(x, cache[i])
                new_caches.append(ci)
            elif _recompute is not None:
                x = _recompute(block, x)
            else:
                x = block(x)
        x = self.ln_f(x)
        if view is not None:
            return x, (view.finalize() if finalize else view)
        if cache is not None:
            return x, new_caches
        return x


class GPTForCausalLM(Layer):
    """LM head with tied embeddings; loss computed from shifted logits."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)
            self.lm_head.weight.pspec = PartitionSpec(None, "mp")

    def forward(self, input_ids, position_ids=None, cache=None):
        if cache is not None:
            x, cache = self.gpt(input_ids, position_ids, cache)
        else:
            x = self.gpt(input_ids, position_ids)
        if self.config.tie_word_embeddings:
            if _mpo.lm_viable(self.config.vocab_size):
                # overlapped LM head: rotate-weights ring over the vocab
                # shards — each step matmuls the resident shard into its
                # logits slice while the next is in flight (no monolithic
                # table all-gather)
                logits = call(lambda xr, w: _mpo.lm_head_matmul(xr, w),
                              x, self.gpt.wte.weight,
                              name="mp_overlap_lm_head")
            else:
                logits = ops.matmul(x, self.gpt.wte.weight,
                                    transpose_y=True)
        else:
            logits = self.lm_head(x)
        if cache is not None:
            return logits, cache
        return logits

    def gen_cache(self, batch_size, dtype="float32", max_len=None,
                  kv_dtype=None):
        """Preallocated static-shape slotted KV cache
        (``serving.cache.SlottedKVCache``): one decode program shape for
        the life of the process.  ``batch_size`` is the number of slots;
        ``max_len`` defaults to the model's position budget.
        ``kv_dtype="int8"`` stores the pool quantized (int8 codes +
        per-(row, head) f32 scales; appends quantize in-program and the
        decode attention dequantizes inline — ``dtype`` then only names
        the compute dtype the cache was built against)."""
        from ..serving.cache import SlottedKVCache
        c = self.config
        return SlottedKVCache.create(
            batch_size, c.num_hidden_layers,
            max_len or c.max_position_embeddings, c.num_attention_heads,
            c.hidden_size // c.num_attention_heads, dtype,
            kv_dtype=kv_dtype)

    def gen_paged_cache(self, batch_size, dtype="float32", max_len=None,
                        page_size=64, kv_dtype=None):
        """Preallocated paged KV cache (``serving.cache.PagedKVCache``)
        with a DENSE identity page table — slot ``i`` owns its own page
        run, so model-level use needs no allocator (the serving engine
        builds the pooled/shared layout through ``serving.pages``).
        ``model(x, cache=paged)`` decodes through the page-gather
        attention path; capacity matches :meth:`gen_cache`.
        ``kv_dtype="int8"`` selects the quantized pool (see
        :meth:`gen_cache`)."""
        from ..serving.cache import PagedKVCache
        c = self.config
        return PagedKVCache.create_dense(
            batch_size, c.num_hidden_layers,
            max_len or c.max_position_embeddings, c.num_attention_heads,
            c.hidden_size // c.num_attention_heads,
            min(int(page_size), int(max_len or c.max_position_embeddings)),
            dtype, kv_dtype=kv_dtype)

    def gen_legacy_concat_cache(self, batch_size, dtype="float32"):
        """COMPAT SHIM — the pre-serving concat-grown cache: the K/V
        arrays grow by one token per step, so the cache SHAPE changes
        every call and any jit around the decode retraces and recompiles
        per generated token.  Kept only for exported-artifact parity and
        old callers; everything new uses :meth:`gen_cache` (static
        slotted) or :meth:`generate`."""
        c = self.config
        empty = ops.zeros(
            [batch_size, 0, c.num_attention_heads,
             c.hidden_size // c.num_attention_heads], dtype)
        return [(empty, empty) for _ in range(c.num_hidden_layers)]

    def generate(self, input_ids, max_new_tokens=20, temperature=1.0,
                 top_k=0, top_p=1.0, eos_token_id=None, seed=0,
                 num_slots=None, max_len=None, greedy=None, **engine_kw):
        """Generate continuations through the serving engine (static
        paged cache + continuous-batching decode — the decode step
        compiles once, not once per token).

        ``input_ids``: (batch, prompt_len) int array (or a list of 1-D
        prompts of different lengths).  Returns a list of 1-D int32
        numpy arrays of generated tokens (prompt excluded).
        ``greedy=True`` is shorthand for temperature 0.  Extra keyword
        arguments reach the engine geometry (``serving.engine_for``):
        ``tp=N`` decodes tensor-parallel over N chips (ISSUE 12),
        ``kv_dtype="int8"`` / ``spec_k=k`` select the quantized /
        speculative modes."""
        from ..serving import generate as _generate
        if greedy:
            temperature = 0.0
        return _generate(self, input_ids, max_new_tokens=max_new_tokens,
                         temperature=temperature, top_k=top_k, top_p=top_p,
                         eos_token_id=eos_token_id, seed=seed,
                         num_slots=num_slots, max_len=max_len,
                         **engine_kw)


class GPTPretrainingCriterion(Layer):
    """Shifted-causal-LM loss (reference analogue: the fleet GPT model's
    criterion)."""

    def forward(self, logits, labels, loss_mask=None):
        # shift via the LABELS, not the logits: slicing logits[:, :-1, :]
        # copies the whole (B, S, V) array (~1GB of HBM traffic at GPT-2
        # bench shapes); rolling the small int labels and masking position
        # S-1 with ignore_index computes the same loss without it
        b, s = labels.shape[0], labels.shape[1]
        targets = ops.concat(
            [labels[:, 1:], ops.full([b, 1], -100, labels.dtype)], axis=1)
        loss = F.cross_entropy(logits, targets, reduction="none",
                               ignore_index=-100)
        denom = float(s - 1) / float(s)  # mean over the S-1 real positions
        if loss_mask is not None:
            mask = ops.concat(
                [loss_mask[:, 1:], ops.zeros([b, 1], loss_mask.dtype)],
                axis=1)
            return ops.sum(loss * mask) / ops.maximum(
                ops.sum(mask), ops.to_tensor(1.0))
        return ops.mean(loss) / denom


def gpt2_345m():
    return GPTForCausalLM(GPTConfig.gpt2_medium())
