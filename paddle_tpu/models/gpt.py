"""GPT-2 — the flagship language model (reference capability target:
BASELINE.md config 4, "GPT-2 345M ... fused attention/FFN"; the reference's
closest in-tree models are fleet's GPT test models,
python/paddle/fluid/tests/unittests/auto_parallel_gpt_model.py).

TPU-first design:
* pre-LN transformer, bf16-friendly, weight-tied logits
* attention via F.scaled_dot_product_attention -> Pallas flash kernel
* Megatron sharding ANNOTATIONS baked into the parameters (pspec): qkv/fc1
  column-sharded on 'mp', out-proj/fc2 row-sharded, embeddings vocab-sharded;
  activations constrained to ('dp', 'sep', None) so sequence parallelism
  shards the token axis.  Under pjit these annotations are the whole
  distribution strategy (GSPMD inserts the collectives the reference's
  mp_layers/c_* ops hand-coded).
* vocab padded to a multiple of 128 so the logits matmul tiles the MXU.
"""
from __future__ import annotations

import dataclasses
import math

from jax.sharding import PartitionSpec

from .. import ops
from ..core.tensor import Tensor
from ..distributed.mp_layers import with_sharding_constraint
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.layers import Layer, LayerList
from ..nn.layer.norm import LayerNorm


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304          # 50257 padded to 128-multiple (MXU tiling)
    max_position_embeddings: int = 1024
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: int = 4096
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True
    # activation recompute per block (jax.checkpoint): trades ~1/3 more
    # FLOPs for O(sqrt)-ish activation memory — required for long-sequence
    # training (s=8192 without it sits at the 16GB HBM edge on one v5e)
    use_recompute: bool = False

    @classmethod
    def gpt2_small(cls):
        return cls(hidden_size=768, num_hidden_layers=12,
                   num_attention_heads=12, intermediate_size=3072)

    @classmethod
    def gpt2_medium(cls):  # the 345M benchmark config
        return cls(hidden_size=1024, num_hidden_layers=24,
                   num_attention_heads=16, intermediate_size=4096)

    @classmethod
    def gpt2_large(cls):
        return cls(hidden_size=1280, num_hidden_layers=36,
                   num_attention_heads=20, intermediate_size=5120)

    @classmethod
    def tiny(cls):  # for tests
        return cls(vocab_size=512, max_position_embeddings=128,
                   hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=128,
                   hidden_dropout_prob=0.0, attention_dropout_prob=0.0)


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_attention_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        self.hidden_size = c.hidden_size
        init = I.Normal(0.0, c.initializer_range)
        out_init = I.Normal(0.0, c.initializer_range
                            / math.sqrt(2 * c.num_hidden_layers))
        self.qkv_proj = Linear(c.hidden_size, 3 * c.hidden_size)
        self.qkv_proj.weight.set_value(Tensor(init((c.hidden_size,
                                                    3 * c.hidden_size))))
        self.out_proj = Linear(c.hidden_size, c.hidden_size)
        self.out_proj.weight.set_value(Tensor(out_init((c.hidden_size,
                                                        c.hidden_size))))
        self.attn_dropout_p = c.attention_dropout_prob
        self.resid_dropout = Dropout(c.hidden_dropout_prob)
        # Megatron layout: qkv column-sharded, out row-sharded
        self.qkv_proj.weight.pspec = PartitionSpec(None, "mp")
        self.qkv_proj.bias.pspec = PartitionSpec("mp")
        self.out_proj.weight.pspec = PartitionSpec("mp", None)

    def forward(self, x, cache=None):
        b, s, _ = x.shape
        qkv = self.qkv_proj(x)
        # q/k/v as contiguous LAST-DIM slices of the fused projection:
        # reshape-to-(b,s,3,h,d)+unbind forces a transposed-layout copy of
        # the whole qkv activation per layer (~0.1 ms × 24 layers × fwd+bwd
        # on the 345M bench); last-dim slices are free
        h = self.hidden_size
        q = ops.reshape(qkv[:, :, :h], [b, s, self.num_heads, self.head_dim])
        k = ops.reshape(qkv[:, :, h:2 * h],
                        [b, s, self.num_heads, self.head_dim])
        v = ops.reshape(qkv[:, :, 2 * h:],
                        [b, s, self.num_heads, self.head_dim])
        if cache is not None:
            pk, pv = cache
            k = ops.concat([pk, k], axis=1)
            v = ops.concat([pv, v], axis=1)
            cache = (k, v)
        # always causal: the reference SDPA mask is end-aligned
        # (tril offset sk-sq), which is exactly right for cached decode —
        # each new token sees the full past plus itself, never its future
        out = F.scaled_dot_product_attention(
            q, k, v, dropout_p=self.attn_dropout_p, is_causal=True,
            training=self.training)
        out = ops.reshape(out, [b, s, self.hidden_size])
        out = self.resid_dropout(self.out_proj(out))
        if cache is not None:
            return out, cache
        return out


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        init = I.Normal(0.0, c.initializer_range)
        out_init = I.Normal(0.0, c.initializer_range
                            / math.sqrt(2 * c.num_hidden_layers))
        self.fc1 = Linear(c.hidden_size, c.intermediate_size)
        self.fc1.weight.set_value(Tensor(init((c.hidden_size,
                                               c.intermediate_size))))
        self.fc2 = Linear(c.intermediate_size, c.hidden_size)
        self.fc2.weight.set_value(Tensor(out_init((c.intermediate_size,
                                                   c.hidden_size))))
        self.dropout = Dropout(c.hidden_dropout_prob)
        self.fc1.weight.pspec = PartitionSpec(None, "mp")
        self.fc1.bias.pspec = PartitionSpec("mp")
        self.fc2.weight.pspec = PartitionSpec("mp", None)

    def forward(self, x):
        return self.dropout(self.fc2(F.gelu(self.fc1(x), approximate=True)))


class GPTBlock(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln1 = LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln2 = LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)

    def forward(self, x, cache=None):
        if cache is not None:
            a, cache = self.attn(self.ln1(x), cache)
            x = x + a
        else:
            x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        # sequence-parallel activation layout: tokens sharded over 'sep'
        x = with_sharding_constraint(x, PartitionSpec("dp", "sep", None))
        if cache is not None:
            return x, cache
        return x


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        c = config
        init = I.Normal(0.0, c.initializer_range)
        self.wte = Embedding(c.vocab_size, c.hidden_size)
        self.wte.weight.set_value(Tensor(init((c.vocab_size, c.hidden_size))))
        self.wte.weight.pspec = PartitionSpec("mp", None)   # vocab-parallel
        self.wpe = Embedding(c.max_position_embeddings, c.hidden_size)
        self.wpe.weight.set_value(
            Tensor(init((c.max_position_embeddings, c.hidden_size))))
        self.drop = Dropout(c.hidden_dropout_prob)
        self.h = LayerList([GPTBlock(c) for _ in range(c.num_hidden_layers)])
        self.ln_f = LayerNorm(c.hidden_size, c.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None, cache=None):
        b, s = input_ids.shape
        if position_ids is None:
            start = 0 if cache is None else cache[0][0].shape[1]
            position_ids = ops.arange(start, start + s, dtype="int32")
            position_ids = ops.unsqueeze(position_ids, 0)
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        x = with_sharding_constraint(x, PartitionSpec("dp", "sep", None))
        new_caches = []
        if self.config.use_recompute and self.training and cache is None:
            from ..distributed.recompute import recompute as _recompute
        else:
            _recompute = None
        for i, block in enumerate(self.h):
            if cache is not None:
                x, ci = block(x, cache[i])
                new_caches.append(ci)
            elif _recompute is not None:
                x = _recompute(block, x)
            else:
                x = block(x)
        x = self.ln_f(x)
        if cache is not None:
            return x, new_caches
        return x


class GPTForCausalLM(Layer):
    """LM head with tied embeddings; loss computed from shifted logits."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)
            self.lm_head.weight.pspec = PartitionSpec(None, "mp")

    def forward(self, input_ids, position_ids=None, cache=None):
        if cache is not None:
            x, cache = self.gpt(input_ids, position_ids, cache)
        else:
            x = self.gpt(input_ids, position_ids)
        if self.config.tie_word_embeddings:
            logits = ops.matmul(x, self.gpt.wte.weight, transpose_y=True)
        else:
            logits = self.lm_head(x)
        if cache is not None:
            return logits, cache
        return logits

    def gen_cache(self, batch_size, dtype="float32"):
        c = self.config
        empty = ops.zeros(
            [batch_size, 0, c.num_attention_heads,
             c.hidden_size // c.num_attention_heads], dtype)
        return [(empty, empty) for _ in range(c.num_hidden_layers)]


class GPTPretrainingCriterion(Layer):
    """Shifted-causal-LM loss (reference analogue: the fleet GPT model's
    criterion)."""

    def forward(self, logits, labels, loss_mask=None):
        # shift via the LABELS, not the logits: slicing logits[:, :-1, :]
        # copies the whole (B, S, V) array (~1GB of HBM traffic at GPT-2
        # bench shapes); rolling the small int labels and masking position
        # S-1 with ignore_index computes the same loss without it
        b, s = labels.shape[0], labels.shape[1]
        targets = ops.concat(
            [labels[:, 1:], ops.full([b, 1], -100, labels.dtype)], axis=1)
        loss = F.cross_entropy(logits, targets, reduction="none",
                               ignore_index=-100)
        denom = float(s - 1) / float(s)  # mean over the S-1 real positions
        if loss_mask is not None:
            mask = ops.concat(
                [loss_mask[:, 1:], ops.zeros([b, 1], loss_mask.dtype)],
                axis=1)
            return ops.sum(loss * mask) / ops.maximum(
                ops.sum(mask), ops.to_tensor(1.0))
        return ops.mean(loss) / denom


def gpt2_345m():
    return GPTForCausalLM(GPTConfig.gpt2_medium())
