"""Model zoo: language models (GPT-2 flagship) + vision re-exports."""
from ..vision.models import (LeNet, ResNet, resnet18, resnet50)  # noqa: F401
from .gpt import (GPTConfig, GPTForCausalLM, GPTModel,  # noqa: F401
                  GPTPretrainingCriterion, gpt2_345m)
