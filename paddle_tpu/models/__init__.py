"""Model zoo: language models (GPT-2 flagship) + vision re-exports."""
from ..vision.models import (DenseNet, GoogLeNet, InceptionV3,  # noqa: F401
                             LeNet, MobileNetV3Large, MobileNetV3Small,
                             ResNet, ShuffleNetV2, SqueezeNet, densenet121,
                             googlenet, inception_v3, mobilenet_v3_large,
                             mobilenet_v3_small, resnet18, resnet50,
                             shufflenet_v2_x1_0, squeezenet1_1)
from .gpt import (GPTConfig, GPTForCausalLM, GPTModel,  # noqa: F401
                  GPTPretrainingCriterion, gpt2_345m)
