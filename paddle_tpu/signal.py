"""paddle.signal — frame / overlap_add / stft / istft.

Reference surface and semantics: python/paddle/signal.py (frame at :32,
overlap_add at :154, stft at :237, istft at :391 — backed by the frame /
overlap_add phi kernels and fft_r2c/c2c/c2r).

TPU-native: frame is a static gather (the index grid is a compile-time
constant, so XLA lowers it to strided slices); overlap_add is one
scatter-add; the DFTs ride jnp.fft like paddle_tpu.fft.  All four are
differentiable and jit-safe (static shapes from static frame/hop args).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .core.dispatch import wrap_op

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_index_grid(frame_length, hop_length, num_frames, axis):
    if axis == -1:
        # [..., frame_length, num_frames]
        return (np.arange(frame_length)[:, None]
                + hop_length * np.arange(num_frames)[None, :])
    # axis == 0: [num_frames, frame_length, ...]
    return (hop_length * np.arange(num_frames)[:, None]
            + np.arange(frame_length)[None, :])


def _check_frame_args(frame_length, hop_length, axis):
    if axis not in (0, -1):
        raise ValueError(f"Unexpected axis: {axis}. It should be 0 or -1.")
    if not isinstance(frame_length, int) or frame_length <= 0:
        raise ValueError(f"Unexpected frame_length: {frame_length}. "
                         "It should be an positive integer.")
    if not isinstance(hop_length, int) or hop_length <= 0:
        raise ValueError(f"Unexpected hop_length: {hop_length}. "
                         "It should be an positive integer.")


def _frame_raw(x, frame_length, hop_length, axis):
    seq_len = x.shape[axis]
    if frame_length > seq_len:
        raise ValueError(
            "Attribute frame_length should be less equal than sequence "
            f"length, but got ({frame_length}) > ({seq_len}).")
    num_frames = 1 + (seq_len - frame_length) // hop_length
    idx = _frame_index_grid(frame_length, hop_length, num_frames, axis)
    if axis == -1:
        return x[..., idx]
    return x[idx]


@wrap_op
def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into (overlapping) frames — reference signal.py:32.

    axis=-1: [..., seq] -> [..., frame_length, num_frames];
    axis=0:  [seq, ...] -> [num_frames, frame_length, ...]."""
    _check_frame_args(frame_length, hop_length, axis)
    return _frame_raw(x, frame_length, hop_length, axis)


def _overlap_add_raw(x, hop_length, axis):
    if axis == -1:
        frame_length, num_frames = x.shape[-2], x.shape[-1]
    else:
        num_frames, frame_length = x.shape[0], x.shape[1]
    out_len = (num_frames - 1) * hop_length + frame_length
    idx = _frame_index_grid(frame_length, hop_length, num_frames, axis)
    if axis == -1:
        out = jnp.zeros(x.shape[:-2] + (out_len,), x.dtype)
        return out.at[..., idx].add(x)
    out = jnp.zeros((out_len,) + x.shape[2:], x.dtype)
    return out.at[idx].add(x)


@wrap_op
def overlap_add(x, hop_length, axis=-1, name=None):
    """Reconstruct by adding overlapping frames — reference signal.py:154.

    axis=-1: [..., frame_length, num_frames] -> [..., seq];
    axis=0:  [num_frames, frame_length, ...] -> [seq, ...]."""
    if axis not in (0, -1):
        raise ValueError(f"Unexpected axis: {axis}. It should be 0 or -1.")
    if not isinstance(hop_length, int) or hop_length <= 0:
        raise ValueError(f"Unexpected hop_length: {hop_length}. "
                         "It should be an positive integer.")
    if x.ndim < 2:
        raise ValueError("overlap_add expects an input of at least rank 2, "
                         f"got rank {x.ndim}")
    return _overlap_add_raw(x, hop_length, axis)


def _prep_window(window, win_length, n_fft, like_dtype):
    if window is None:
        window = jnp.ones((win_length,), like_dtype)
    else:
        window = jnp.asarray(window)
        if window.ndim != 1 or window.shape[0] != win_length:
            raise ValueError(
                "expected a 1D window tensor of size equal to "
                f"win_length({win_length}), but got window with shape "
                f"{window.shape}.")
    if win_length < n_fft:
        pad_left = (n_fft - win_length) // 2
        window = jnp.pad(window,
                         (pad_left, n_fft - win_length - pad_left))
    return window


def _stft_raw(x, window, n_fft, hop_length, win_length, center, pad_mode,
              normalized, onesided):
    x_rank = x.ndim
    if x_rank == 1:
        x = x[None]
    if center:
        if pad_mode not in ("constant", "reflect"):
            raise ValueError('pad_mode should be "reflect" or "constant", '
                             f'but got "{pad_mode}".')
        pad = n_fft // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)],
                    mode=("reflect" if pad_mode == "reflect"
                          else "constant"))
    if n_fft > x.shape[-1]:
        raise ValueError(f"n_fft should be in (0, seq_length"
                         f"({x.shape[-1]})], but got {n_fft}.")
    frames = _frame_raw(x, n_fft, hop_length, -1)      # (B, n_fft, T)
    frames = jnp.swapaxes(frames, -1, -2)              # (B, T, n_fft)
    frames = frames * window.astype(frames.dtype)
    norm = "ortho" if normalized else "backward"
    if jnp.iscomplexobj(frames):
        out = jnp.fft.fft(frames, axis=-1, norm=norm)
    elif onesided:
        out = jnp.fft.rfft(frames, axis=-1, norm=norm)
    else:
        out = jnp.fft.fft(frames.astype(
            jnp.complex64 if frames.dtype == jnp.float32
            else jnp.complex128), axis=-1, norm=norm)
    out = jnp.swapaxes(out, -1, -2)                    # (B, F, T)
    if x_rank == 1:
        out = out[0]
    return out


@wrap_op
def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform — reference signal.py:237 semantics
    (center/pad_mode/normalized/onesided, win_length center-padding)."""
    if x.ndim not in (1, 2):
        raise ValueError("x should be a 1D or 2D real tensor, but got rank "
                         f"of x is {x.ndim}")
    if hop_length is None:
        hop_length = int(n_fft // 4)
    if hop_length <= 0:
        raise ValueError(f"hop_length should be > 0, but got {hop_length}.")
    if win_length is None:
        win_length = n_fft
    if not 0 < win_length <= n_fft:
        raise ValueError(f"win_length should be in (0, n_fft({n_fft})], "
                         f"but got {win_length}.")
    if jnp.iscomplexobj(x) and onesided:
        raise ValueError("onesided should be False when input or window is "
                         "a complex Tensor.")
    win = _prep_window(window, win_length, n_fft,
                       jnp.asarray(x).real.dtype)
    return _stft_raw(jnp.asarray(x), win, n_fft, hop_length, win_length,
                     center, pad_mode, normalized, onesided)


@wrap_op
def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT (least-squares / NOLA-weighted overlap-add) — reference
    signal.py:391 semantics incl. the NOLA constraint check.

    jit-time caveat: the NOLA (Nonzero Overlap-Add) violation check is a
    HOST-side ValueError and can only run on concrete values.  Under
    jit/trace the envelope is a tracer, so the check is skipped; the
    division is instead guarded with ``jnp.where(envelope > eps, ...)``
    so a traced NOLA violation yields the un-normalized overlap-add in the
    near-zero bins rather than silently emitting inf/nan.  Call once
    eagerly (or run scipy.signal.check_NOLA) to validate a new window/hop
    configuration before jitting."""
    if x.ndim not in (2, 3):
        raise ValueError("x should be a 2D or 3D complex tensor, but got "
                         f"rank of x is {x.ndim}")
    if not jnp.iscomplexobj(x):
        raise TypeError("istft expects a complex input (the output of "
                        "stft); got dtype %s" % (x.dtype,))
    x_rank = x.ndim
    if x_rank == 2:
        x = x[None]
    if hop_length is None:
        hop_length = int(n_fft // 4)
    if win_length is None:
        win_length = n_fft
    if not 0 < hop_length <= win_length:
        raise ValueError(f"hop_length should be in (0, win_length"
                         f"({win_length})], but got {hop_length}.")
    if not 0 < win_length <= n_fft:
        raise ValueError(f"win_length should be in (0, n_fft({n_fft})], "
                         f"but got {win_length}.")
    fft_size = x.shape[-2]
    if onesided and fft_size != n_fft // 2 + 1:
        raise ValueError("fft_size should be equal to n_fft // 2 + 1"
                         f"({n_fft // 2 + 1}) when onesided is True, but "
                         f"got {fft_size}.")
    if not onesided and fft_size != n_fft:
        raise ValueError(f"fft_size should be equal to n_fft({n_fft}) when "
                         f"onesided is False, but got {fft_size}.")
    real_dtype = (jnp.float32 if x.dtype == jnp.complex64 else jnp.float64)
    win = _prep_window(window, win_length, n_fft, real_dtype)
    if return_complex and onesided:
        raise ValueError("onesided should be False when input(output of "
                         "istft) or window is a complex Tensor.")
    if not return_complex and jnp.iscomplexobj(win):
        raise ValueError("Data type of window should not be complex when "
                         "return_complex is False.")

    n_frames = x.shape[-1]
    frames = jnp.swapaxes(x, -1, -2)                   # (B, T, F)
    norm = "ortho" if normalized else "backward"
    if return_complex:
        out = jnp.fft.ifft(frames, axis=-1, norm=norm)
    else:
        if not onesided:
            frames = frames[..., :n_fft // 2 + 1]
        out = jnp.fft.irfft(frames, n=n_fft, axis=-1, norm=norm)
    out = out * win.astype(out.dtype)
    out = jnp.swapaxes(out, -1, -2)                    # (B, n_fft, T)
    out = _overlap_add_raw(out, hop_length, -1)        # (B, L)

    env_frames = jnp.tile((win * win)[None], (n_frames, 1)).T  # (n_fft, T)
    envelop = _overlap_add_raw(env_frames, hop_length, -1)     # (L,)

    if length is None:
        if center:
            out = out[:, n_fft // 2:-(n_fft // 2)]
            envelop = envelop[n_fft // 2:-(n_fft // 2)]
    else:
        start = n_fft // 2 if center else 0
        out = out[:, start:start + length]
        envelop = envelop[start:start + length]

    if not isinstance(envelop, jax.core.Tracer):
        if float(jnp.min(jnp.abs(envelop))) < 1e-11:
            raise ValueError(
                "Abort istft because Nonzero Overlap Add (NOLA) condition "
                "failed. For more information about NOLA constraint please "
                "see scipy.signal.check_NOLA.")
    # traced-safe division: under jit the host-side NOLA check above cannot
    # run, and dividing by a ~0 envelope bin would silently emit inf/nan
    # into the output — guard with a where (envelope = sum(win^2) >= 0, so
    # the eps compare matches the eager check's threshold; see docstring)
    envelop_safe = jnp.where(envelop > 1e-11, envelop,
                             jnp.ones_like(envelop))
    out = out / envelop_safe.astype(out.dtype)
    if x_rank == 2:
        out = out[0]
    return out
