"""paddle.incubate graph/segment/fused-softmax operators (reference:
python/paddle/incubate/__init__.py __all__ — segment_sum/mean/max/min
(incubate/tensor/math.py over phi segment_pool), graph_send_recv
(incubate/operators/graph_send_recv.py:22), graph_sample_neighbors
(graph_sample_neighbors.py:23), graph_reindex (graph_reindex.py:23),
graph_khop_sampler (graph_khop_sampler.py:23), softmax_mask_fuse(.py:23)
and softmax_mask_fuse_upper_triangle).

TPU-native notes:
* segment reductions ride jax.ops.segment_* (differentiable, jit-safe when
  the caller's ids are static-shaped; empty segments produce 0 like the
  reference's phi kernels, not -inf).
* the graph SAMPLING ops are host-side numpy: their output shapes are
  data-dependent (number of sampled edges), which no static-shape compiler
  can express — the reference runs them as eager CUDA ops in the input
  pipeline, and here they run eagerly on host exactly where a DataLoader
  would call them.
* softmax_mask_fuse is a plain composition — XLA fuses the add into the
  softmax, which is the entire point of the reference's hand-fused CUDA op.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import wrap_op
from ..core.tensor import Tensor

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "graph_send_recv", "graph_sample_neighbors", "graph_reindex",
           "graph_khop_sampler", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle"]


def _arr(x):
    return x._array if isinstance(x, Tensor) else jnp.asarray(x)


def _np(x):
    return np.asarray(x.numpy() if isinstance(x, Tensor) else x)


def _num_segments(segment_ids):
    ids = _arr(segment_ids)
    if isinstance(ids, jax.core.Tracer):
        raise ValueError(
            "segment ids must be concrete (the output row count is "
            "data-dependent); run segment ops eagerly or pad ids and pass "
            "through jax.ops.segment_sum(num_segments=...) directly")
    return int(jnp.max(ids)) + 1 if ids.size else 0


def _segment_pool(d, ids, n, pool):
    """Shared pooling core (reference segment_pool semantics: empty
    segments are 0, not +-inf; mean divides by the real count)."""
    if pool == "sum":
        return jax.ops.segment_sum(d, ids, n)
    counts = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), ids, n)
    shape = (-1,) + (1,) * (d.ndim - 1)
    if pool == "mean":
        s = jax.ops.segment_sum(d, ids, n)
        return s / jnp.maximum(counts, 1).reshape(shape)
    red = jax.ops.segment_max if pool == "max" else jax.ops.segment_min
    out = red(d, ids, n)
    empty = (counts == 0).reshape(shape)
    return jnp.where(empty, jnp.zeros((), d.dtype), out)


def _segment(pool):
    @wrap_op
    def op(data, segment_ids, name=None):
        n = _num_segments(segment_ids)
        ids = jnp.asarray(_arr(segment_ids), jnp.int32)
        return _segment_pool(_arr(data), ids, n, pool)
    op.__name__ = "segment_" + pool
    return op


segment_sum = _segment("sum")
segment_mean = _segment("mean")
segment_max = _segment("max")
segment_min = _segment("min")


@wrap_op
def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Message passing gather-scatter (reference graph_send_recv.py:22):
    gather ``x[src_index]``, segment-reduce onto ``dst_index`` rows of a
    (out_size or x.shape[0])-row output."""
    if pool_type not in ("sum", "mean", "max", "min"):
        raise ValueError(
            "pool_type should be `sum`, `mean`, `max` or `min`, but "
            "received %s" % pool_type)
    xa = _arr(x)
    src = jnp.asarray(_arr(src_index), jnp.int32)
    dst = jnp.asarray(_arr(dst_index), jnp.int32)
    n = int(out_size) if out_size else xa.shape[0]
    return _segment_pool(jnp.take(xa, src, axis=0), dst, n, pool_type)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, name=None):
    """Uniform neighbor sampling over a CSC graph (reference
    graph_sample_neighbors.py:23).  Host-side (data-dependent output
    shape).  Returns (out_neighbors, out_count[, out_eids])."""
    row_np = _np(row).reshape(-1)
    colptr_np = _np(colptr).reshape(-1)
    nodes = _np(input_nodes).reshape(-1)
    eids_np = _np(eids).reshape(-1) if eids is not None else None
    if return_eids and eids_np is None:
        raise ValueError("`eids` should not be None if `return_eids` "
                         "is True.")
    rng = None
    out_n, out_c, out_e = [], [], []
    for node in nodes:
        lo, hi = int(colptr_np[node]), int(colptr_np[node + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            pick = np.arange(lo, hi)
        else:
            if rng is None:
                # deterministic under paddle.seed (derived from the
                # framework PRNG stream) — drawn LAZILY so a fully
                # deterministic call (sample_size=-1 / small degrees)
                # does not advance the global key stream
                from ..core import random as _rnd
                seed = int(jax.random.randint(_rnd.next_key(), (), 0,
                                              2**31 - 1))
                rng = np.random.default_rng(seed)
            pick = lo + rng.choice(deg, size=sample_size, replace=False)
        out_n.append(row_np[pick])
        out_c.append(len(pick))
        if eids_np is not None:
            out_e.append(eids_np[pick])
    neighbors = Tensor(jnp.asarray(
        np.concatenate(out_n) if out_n else np.zeros(0, row_np.dtype)))
    count = Tensor(jnp.asarray(np.asarray(out_c, np.int32)))
    if return_eids:
        return neighbors, count, Tensor(jnp.asarray(
            np.concatenate(out_e) if out_e else np.zeros(0, row_np.dtype)))
    return neighbors, count


def _first_appearance_index(*id_arrays):
    """Shared reindex core: one {orig id -> local id} mapping built in
    first-appearance order across the given arrays, plus the ordered
    unique id list."""
    mapping = {}
    out_nodes = []
    for arr in id_arrays:
        for n in arr:
            n = int(n)
            if n not in mapping:
                mapping[n] = len(out_nodes)
                out_nodes.append(n)
    return mapping, out_nodes


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Reindex sampled neighbors to local ids (reference
    graph_reindex.py:23): out_nodes = [x, then unseen neighbors in
    first-appearance order]; returns (reindex_src, reindex_dst,
    out_nodes)."""
    x_np = _np(x).reshape(-1)
    nbr = _np(neighbors).reshape(-1)
    cnt = _np(count).reshape(-1)
    mapping, out_nodes = _first_appearance_index(x_np, nbr)
    src = np.asarray([mapping[int(n)] for n in nbr], np.int64)
    dst = np.repeat(np.arange(len(x_np), dtype=np.int64), cnt)
    dt = x_np.dtype
    return (Tensor(jnp.asarray(src.astype(dt))),
            Tensor(jnp.asarray(dst.astype(dt))),
            Tensor(jnp.asarray(np.asarray(out_nodes, dt))))


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop sampling + subgraph reindex (reference
    graph_khop_sampler.py:23).  Returns (edge_src, edge_dst, sample_index,
    reindex_nodes[, edge_eids])."""
    if return_eids and sorted_eids is None:
        raise ValueError("`sorted_eid` should not be None if return_eids "
                         "is True.")
    nodes = _np(input_nodes).reshape(-1)
    frontier = nodes
    all_centers, all_neighbors, all_counts, all_eids = [], [], [], []
    for size in list(sample_sizes):
        res = graph_sample_neighbors(row, colptr, Tensor(jnp.asarray(
            frontier)), eids=sorted_eids, sample_size=int(size),
            return_eids=return_eids)
        nbr, cnt = _np(res[0]), _np(res[1])
        all_centers.append(frontier)
        all_neighbors.append(nbr)
        all_counts.append(cnt)
        if return_eids:
            all_eids.append(_np(res[2]))
        # next frontier: newly-discovered unique neighbors
        seen = set(int(v) for f in all_centers for v in f)
        frontier = np.asarray(
            [v for v in dict.fromkeys(int(n) for n in nbr)
             if v not in seen], dtype=nodes.dtype)
        if frontier.size == 0:
            frontier = np.zeros(0, nodes.dtype)
    centers = np.concatenate(
        [np.repeat(c, ct) for c, ct in zip(all_centers, all_counts)]) \
        if all_centers else np.zeros(0, nodes.dtype)
    neighbors = (np.concatenate(all_neighbors)
                 if all_neighbors else np.zeros(0, nodes.dtype))
    # reindex: inputs first, then neighbors/centers in appearance order
    rest = (np.concatenate([centers, neighbors]) if centers.size
            else np.zeros(0, nodes.dtype))
    mapping, out_nodes = _first_appearance_index(nodes, rest)
    dt = nodes.dtype
    edge_src = np.asarray([mapping[int(n)] for n in neighbors], dt)
    edge_dst = np.asarray([mapping[int(c)] for c in centers], dt)
    sample_index = np.asarray(out_nodes, dt)
    reindex_nodes = np.asarray([mapping[int(n)] for n in nodes], dt)
    outs = (Tensor(jnp.asarray(edge_src)), Tensor(jnp.asarray(edge_dst)),
            Tensor(jnp.asarray(sample_index)),
            Tensor(jnp.asarray(reindex_nodes)))
    if return_eids:
        eids = (np.concatenate(all_eids)
                if all_eids else np.zeros(0, nodes.dtype))
        return outs + (Tensor(jnp.asarray(eids)),)
    return outs


@wrap_op
def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) — reference softmax_mask_fuse.py:23 (the CUDA
    fusion is XLA's job here; stats in f32 like the rest of the stack)."""
    xa, ma = _arr(x), _arr(mask)
    out = jax.nn.softmax(xa.astype(jnp.float32) + ma.astype(jnp.float32),
                         axis=-1)
    return out.astype(xa.dtype)


@wrap_op
def softmax_mask_fuse_upper_triangle(x, name=None):
    """softmax with the upper triangle masked out (causal attention
    scores) — reference softmax_mask_fuse_upper_triangle."""
    xa = _arr(x)
    sq, sk = xa.shape[-2], xa.shape[-1]
    visible = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
    logits = jnp.where(visible, xa.astype(jnp.float32),
                       jnp.float32(-1e30))
    return jax.nn.softmax(logits, axis=-1).astype(xa.dtype)
