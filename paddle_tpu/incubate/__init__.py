"""paddle_tpu.incubate (reference surface: python/paddle/incubate/)."""
from . import autograd  # noqa: F401
from . import checkpoint  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
