"""paddle_tpu.incubate (reference surface: python/paddle/incubate/)."""
from . import checkpoint  # noqa: F401
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from . import operators  # noqa: F401
from . import tensor  # noqa: F401
from .graph_ops import (graph_khop_sampler, graph_reindex,  # noqa: F401
                        graph_sample_neighbors, graph_send_recv,
                        segment_max, segment_mean, segment_min,
                        segment_sum, softmax_mask_fuse,
                        softmax_mask_fuse_upper_triangle)


def __getattr__(name):
    # `incubate.autograd` is deprecated (folded into paddle_tpu.autograd)
    # — imported lazily so its DeprecationWarning fires at USE, not on
    # every `import paddle_tpu`
    if name == "autograd":
        from . import autograd
        return autograd
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
