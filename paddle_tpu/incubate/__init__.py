"""paddle_tpu.incubate (reference surface: python/paddle/incubate/)."""
from . import nn  # noqa: F401
