"""paddle.incubate.operators (reference module path:
python/paddle/incubate/operators/__init__.py) — the graph/fused-softmax
operators re-exported from incubate.graph_ops."""
from ..graph_ops import (graph_khop_sampler, graph_reindex,  # noqa: F401
                         graph_sample_neighbors, graph_send_recv,
                         softmax_mask_fuse,
                         softmax_mask_fuse_upper_triangle)

__all__ = ["graph_send_recv", "graph_khop_sampler",
           "graph_sample_neighbors", "graph_reindex",
           "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle"]
