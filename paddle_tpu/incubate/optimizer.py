"""paddle.incubate.optimizer — LookAhead and ModelAverage (reference:
python/paddle/incubate/optimizer/{lookahead.py,modelaverage.py}).

Both wrap an inner optimizer on the eager path: LookAhead keeps slow copies
of the parameters and interpolates every k steps (Zhang et al. 2019);
ModelAverage maintains a running average of parameters applied for
evaluation (apply/restore)."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """lookahead.py: slow_t+1 = slow_t + alpha * (fast - slow_t) every k
    inner steps; fast weights reset to the slow ones."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be in [0, 1], got {alpha}")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        self._slow = {}

    def _params(self):
        return [p for p in self.inner_optimizer._parameter_list
                if not p.stop_gradient]

    def step(self):
        if not self._slow:
            # slow weights start at the step-0 parameters (BEFORE the first
            # inner update), so the first sync at step k interpolates
            # slow_0 + alpha*(fast_k - slow_0) like the reference
            for p in self._params():
                self._slow[id(p)] = p._array
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k:
            return
        for p in self._params():
            slow = self._slow.get(id(p), p._array)
            slow = slow + self.alpha * (p._array - slow)
            self._slow[id(p)] = slow
            p._array = jnp.asarray(slow).astype(p._array.dtype)

    def clear_grad(self, *a, **k):
        self.inner_optimizer.clear_grad(*a, **k)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        import numpy as np
        # slow copies keyed by parameter ORDER (ids are process-local)
        slow = {str(i): np.asarray(self._slow[id(p)])
                for i, p in enumerate(self._params()) if id(p) in self._slow}
        return {"inner": self.inner_optimizer.state_dict(),
                "step_num": self._step_num, "slow": slow}

    def set_state_dict(self, sd):
        self.inner_optimizer.set_state_dict(sd["inner"])
        self._step_num = sd.get("step_num", 0)
        self._slow = {}
        for i, p in enumerate(self._params()):
            if str(i) in sd.get("slow", {}):
                self._slow[id(p)] = jnp.asarray(sd["slow"][str(i)])


class ModelAverage:
    """modelaverage.py: running parameter average over a sliding window;
    ``apply()`` swaps averaged params in for evaluation, ``restore()``
    swaps the training params back."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.average_window_rate = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._parameters = list(parameters or [])
        self._sum = {}
        self._count = {}
        self._backup = None

    def step(self):
        """Accumulate the current parameter values into the average."""
        for p in self._parameters:
            if p.stop_gradient:
                continue
            pid = id(p)
            n = self._count.get(pid, 0)
            window = max(self.min_average_window,
                         min(self.max_average_window,
                             int(n * self.average_window_rate) or 1))
            if n >= window:
                # slide: decay old contribution (restart accumulation)
                self._sum[pid] = self._sum[pid] * (window - 1) / window
                n = window - 1
            acc = self._sum.get(pid)
            self._sum[pid] = p._array.astype(jnp.float32) if acc is None \
                else acc + p._array.astype(jnp.float32)
            self._count[pid] = n + 1

    def apply(self, executor=None, need_restore=True):
        """Swap averaged parameters in (for evaluation)."""
        self._backup = {}
        for p in self._parameters:
            pid = id(p)
            if pid not in self._sum:
                continue
            self._backup[pid] = p._array
            avg = self._sum[pid] / self._count[pid]
            p._array = avg.astype(p._array.dtype)
        if not need_restore:
            self._backup = None

    def restore(self, executor=None):
        """Swap the training parameters back after apply()."""
        if self._backup is None:
            return
        for p in self._parameters:
            pid = id(p)
            if pid in self._backup:
                p._array = self._backup[pid]
        self._backup = None

    def minimize(self, loss, **kw):
        raise NotImplementedError(
            "ModelAverage tracks parameters; call step() after the inner "
            "optimizer's step()")
