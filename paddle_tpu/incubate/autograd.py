"""paddle.incubate.autograd (reference: python/paddle/incubate/autograd/ —
functional vjp/jvp/Jacobian/Hessian primitives).

The stable ``paddle.autograd`` package already carries the functional
transforms (they are jax-native here); this module is the incubate-path
alias the reference exposes, plus prim-mode shims (`enable_prim` — on TPU
every trace is already "primitive mode": jax primitives + XLA)."""
from __future__ import annotations

from ..autograd import Hessian, Jacobian, jvp, vjp  # noqa: F401

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "enable_prim",
           "disable_prim", "prim_enabled"]


def enable_prim():
    """No-op: jax traces ARE the primitive graph (the reference lowers ops
    to autodiff primitives to do what jax.vjp/jvp do natively)."""


def disable_prim():
    """No-op (see enable_prim)."""


def prim_enabled() -> bool:
    return True
