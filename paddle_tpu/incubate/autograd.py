"""DEPRECATED — ``paddle_tpu.incubate.autograd`` folded into
``paddle_tpu.autograd``.

The incubate path carried nothing of its own: the functional transforms
(vjp/jvp/Jacobian/Hessian) were already re-exports of the stable package,
and the prim-mode shims (enable_prim/disable_prim/prim_enabled) now live
there too.  Importing this module works but warns; switch to::

    from paddle_tpu.autograd import vjp, jvp, Jacobian, Hessian
    from paddle_tpu.autograd import enable_prim, prim_enabled
"""
from __future__ import annotations

import warnings

warnings.warn(
    "paddle_tpu.incubate.autograd is deprecated and has been folded into "
    "paddle_tpu.autograd — import vjp/jvp/Jacobian/Hessian and the "
    "enable_prim/disable_prim/prim_enabled shims from there instead. "
    "This alias module will be removed.",
    DeprecationWarning, stacklevel=2)

from ..autograd import (Hessian, Jacobian, disable_prim,  # noqa: E402,F401
                        enable_prim, jvp, prim_enabled, vjp)

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "enable_prim",
           "disable_prim", "prim_enabled"]
