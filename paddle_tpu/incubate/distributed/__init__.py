"""paddle.incubate.distributed (reference namespace shim)."""
from . import models  # noqa: F401
