"""paddle.incubate.distributed.models.moe — the reference's MoE import
path (python/paddle/incubate/distributed/models/moe/__init__.py) over the
TPU-native implementation in paddle_tpu.distributed.moe."""
from paddle_tpu.distributed.moe import (ExpertFFN, GShardGate,  # noqa
                                        MoELayer, NaiveGate, SwitchGate,
                                        global_gather, global_scatter)

__all__ = ["MoELayer", "NaiveGate", "SwitchGate", "GShardGate",
           "ExpertFFN", "global_scatter", "global_gather"]
