"""paddle.incubate.distributed.models (reference namespace shim)."""
from . import moe  # noqa: F401
