"""Fault-tolerant checkpointing (reference:
python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py — AutoCheckpointChecker:71,
TrainEpochRange:265 — and checkpoint_saver.py).

TPU-native redesign rather than a port: the unit of persistence is a JAX
pytree (params / optimizer slots / LR / RNG / data-iterator cursor), saved

* **sharded** — each host writes only its addressable shards of every
  `jax.Array` (a ZeRO-sharded slot or GSPMD-sharded param is never gathered
  to one host), with global shape/index metadata for reassembly;
* **async** — the device→host fetch is synchronous (cheap) but pickling and
  disk IO run on a background writer thread, so the training step resumes
  immediately (the analogue of the reference's save-on-another-thread HDFS
  uploads);
* **atomically** — payloads land in a ``.tmp`` directory renamed into place,
  with a ``DONE`` marker written last; a half-written checkpoint is never
  eligible for restore.

Auto-resume = ``TrainEpochRange`` (same name/shape as the reference's
``acp.train_epoch_range``): restores the newest complete checkpoint and
fast-forwards the data stream through ``ResumableIterator``.
"""
from __future__ import annotations

import atexit
import hashlib
import json
import os
import pickle
import queue
import re
import shutil
import sys
import threading
import time
import warnings
import weakref
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ...core.tensor import Tensor
from ...observability import liveness as _liveness
from ...robustness import retry as _retry
from ...robustness.faultpoints import declare as _declare, faultpoint

# liveness beacon over one full checkpoint write (shard + manifest +
# barrier + publish), worker-thread and inline paths alike: the classic
# hang this watchdog exists for is an NFS write that never returns
_liveness.declare_beacon(
    "checkpoint.writer", "one checkpoint save drained by the writer "
    "(shard write + manifest + publish barriers)", deadline=600.0)

__all__ = ["CheckpointManager", "ResumableIterator", "TrainEpochRange",
           "CheckpointWriteError", "CheckpointCorruptionError",
           "NoUsableCheckpointError", "CheckpointFallbackWarning"]

_declare("checkpoint.shard_write",
         "raise before a host's shard pickle hits disk (ENOSPC, EIO)")
_declare("checkpoint.shard_file",
         "mutate the landed shard file pre-publish (torn write, bit rot)")
_declare("checkpoint.publish",
         "raise/crash between shard verification and the DONE marker")
_declare("checkpoint.restore_read",
         "mutate/raise before a shard file is read back at restore")
_declare("train.epoch",
         "TrainEpochRange epoch boundary (Preempt here simulates SIGTERM "
         "between epochs)")


class CheckpointWriteError(RuntimeError):
    """A checkpoint could not be safely published (missing/short shard
    after the write barrier).  The step directory holds no DONE marker."""


class CheckpointCorruptionError(ValueError):
    """A published checkpoint failed integrity verification on restore
    (manifest sha256/size mismatch, unpicklable payload, missing shard)."""


class NoUsableCheckpointError(FileNotFoundError):
    """No checkpoint (of those requested) could be restored.  Subclasses
    FileNotFoundError so pre-hardening callers' handlers keep working."""


class CheckpointFallbackWarning(UserWarning):
    """Emitted when restore skips a corrupt checkpoint for an older one."""


# -- interpreter-exit flush --------------------------------------------------
# The async writer is intentionally a daemon thread (a wedged NFS write must
# not block interpreter exit forever), so queued saves would silently die
# with the process.  Every live manager registers here and is close()d —
# queue drained, on the caller thread if need be — by one atexit hook.
_live_managers: "weakref.WeakSet[CheckpointManager]" = weakref.WeakSet()
_STOP = object()


def _flush_managers_at_exit():
    for mgr in list(_live_managers):
        try:
            mgr.close()
        except BaseException as e:  # the process is exiting: report, go on
            sys.stderr.write(
                "[checkpoint] flush of %r at interpreter exit failed: %r\n"
                % (getattr(mgr, "directory", "?"), e))
            sys.stderr.flush()


atexit.register(_flush_managers_at_exit)


# --------------------------------------------------------------------------
# leaf (de)serialization
# --------------------------------------------------------------------------

class _ShardedLeaf:
    """A jax.Array saved as its host-local shards + reassembly metadata."""

    def __init__(self, arr: jax.Array):
        self.shape = tuple(arr.shape)
        self.dtype = str(arr.dtype)
        self.shards = []  # [(index: tuple of (start, stop) or None, np array)]
        for s in arr.addressable_shards:
            idx = tuple(
                (0 if sl.start is None else sl.start,
                 self.shape[d] if sl.stop is None else sl.stop)
                if isinstance(sl, slice) else sl
                for d, sl in enumerate(s.index))
            self.shards.append((idx, np.asarray(s.data)))

    def assemble(self) -> np.ndarray:
        from ...core.dtype import convert_dtype
        out = np.zeros(self.shape, dtype=convert_dtype(self.dtype))
        # shards with identical indices are replicas; unique indices must
        # partition the array — zero-filling a hole would silently corrupt
        # the restored state, so coverage is validated here
        covered = 0
        seen = set()
        total = int(np.prod(self.shape)) if self.shape else 1
        for idx, data in self.shards:
            sl = tuple(slice(a, b) for a, b in idx)
            out[sl] = data
            if idx not in seen:
                seen.add(idx)
                covered += int(np.prod([b - a for a, b in idx])) if idx else 1
        if covered < total:
            raise ValueError(
                f"sharded checkpoint leaf of shape {self.shape} has only "
                f"{covered}/{total} elements ({len(self.shards)} shards) — "
                "a per-host shard file is missing or torn")
        return out


def _to_host(obj):
    """Fetch device leaves to host containers (runs on the caller thread)."""
    if isinstance(obj, Tensor):
        return _to_host(obj._array)
    if isinstance(obj, jax.Array):
        if getattr(obj, "is_fully_replicated", True) or obj.ndim == 0:
            return np.asarray(obj)
        return _ShardedLeaf(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_host(v) for v in obj)
    return obj


def _host_tree_bytes(obj) -> int:
    """Bytes the deserialized host-side tree holds (the restore-time
    transient the HBM ledger reports) — numpy leaves and sharded-leaf
    pieces; non-array leaves price 0."""
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes)
    if isinstance(obj, _ShardedLeaf):
        return sum(int(a.nbytes) for _idx, a in obj.shards
                   if isinstance(a, np.ndarray))
    if isinstance(obj, dict):
        return sum(_host_tree_bytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_host_tree_bytes(v) for v in obj)
    return 0


def _from_host(obj, template=None):
    """Rebuild arrays; with a ``template`` leaf carrying a sharding, the
    restored value is device_put back onto that sharding (so a restored
    ZeRO/GSPMD state keeps its layout)."""
    if isinstance(obj, _ShardedLeaf):
        full = obj.assemble()
        if template is not None and isinstance(template, jax.Array):
            return jax.device_put(full, template.sharding)
        return full
    if isinstance(obj, np.ndarray):
        if template is not None and isinstance(template, jax.Array):
            return jax.device_put(obj, template.sharding)
        return obj
    if isinstance(obj, dict):
        return {k: _from_host(v, template.get(k) if isinstance(template, dict)
                              else None) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        tmpl = template if isinstance(template, (list, tuple)) else \
            [None] * len(obj)
        return type(obj)(_from_host(v, t) for v, t in zip(obj, tmpl))
    return obj


# --------------------------------------------------------------------------
# manager
# --------------------------------------------------------------------------

class _HashingWriter:
    """File-like pass-through that sha256s and counts what pickle streams
    through it — the manifest's view of the intended shard bytes, with no
    full in-memory serialized copy."""

    def __init__(self, f):
        self._f = f
        self.sha = hashlib.sha256()
        self.nbytes = 0

    def write(self, data):
        self.sha.update(data)
        self.nbytes += len(data)
        return self._f.write(data)


class CheckpointManager:
    """Directory of ``ckpt-<step>`` checkpoints with async sharded save,
    atomic publish, retention, and newest-complete restore.

    Multi-host REQUIREMENT: ``directory`` must be ONE shared filesystem
    (NFS/GCS-fuse/...) visible to every host — each host writes its
    ``host-<i>.ckpt`` shard into the same ``ckpt-<step>`` directory and
    host 0 publishes the DONE marker only after verifying every expected
    shard file is present (per-host local disks would publish a checkpoint
    whose peer shards live elsewhere and only fail at restore)."""

    _STEP_RE = re.compile(r"^ckpt-(\d+)$")

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        os.makedirs(directory, exist_ok=True)
        self._host = jax.process_index()
        self._nhosts = jax.process_count()
        # bounded: save() backpressures rather than stacking full host-RAM
        # copies of the state when IO is slower than the step time
        self._q: "queue.Queue" = queue.Queue(maxsize=2)
        self._err: Optional[BaseException] = None
        self._worker: Optional[threading.Thread] = None
        self._closed = False
        if async_save:
            self._worker = threading.Thread(target=self._drain, daemon=True,
                                            name="checkpoint-writer")
            self._worker.start()
        # fetched once; the NOOP_BEACON singleton when liveness is off
        self._beacon = _liveness.beacon("checkpoint.writer")
        _live_managers.add(self)

    # -- save ---------------------------------------------------------------
    def save(self, step: int, state: Any, wait: bool = False):
        """Snapshot ``state`` (any pytree of Tensors/arrays/py data) as
        checkpoint ``step``.  Device arrays are fetched now; IO happens on
        the writer thread unless ``wait`` or ``async_save=False``."""
        if self._closed:
            raise RuntimeError(
                "CheckpointManager(%r) is closed — no further saves"
                % self.directory)
        if self._err is not None:
            err, self._err = self._err, None
            raise RuntimeError("previous async checkpoint failed") from err
        payload = _to_host(state)
        # multi-host publication needs device barriers (sync_global_devices);
        # those must be issued from the main thread in the same order as the
        # training step's collectives on every host — a barrier on the writer
        # thread could interleave with training collectives and deadlock the
        # pod.  So async applies single-host; multi-host saves synchronously.
        if self.async_save and not wait and self._nhosts == 1:
            self._q.put((step, payload))
        else:
            self._write(step, payload)
        if wait:
            self.wait()

    def _drain(self):
        while True:
            item = self._q.get()
            if item is _STOP:
                self._q.task_done()
                return
            if item is None:
                self._q.task_done()
                continue
            step, payload = item
            try:
                self._write(step, payload)
            except BaseException as e:  # surfaced on next save()/wait()
                self._err = e
            finally:
                self._q.task_done()

    @staticmethod
    def _manifest_name(host: int) -> str:
        return f"host-{host}.manifest.json"

    def _write(self, step: int, payload):
        with self._beacon:
            return self._write_guarded(step, payload)

    def _write_guarded(self, step: int, payload):
        from ...observability import registry as _metrics
        t0 = time.perf_counter()
        final = os.path.join(self.directory, f"ckpt-{step}")
        tmp = final + ".tmp"
        if self._host == 0:
            shutil.rmtree(tmp, ignore_errors=True)
            shutil.rmtree(final, ignore_errors=True)
        # all hosts must see the cleaned tmp dir before anyone writes into
        # it — otherwise host 0's rmtree can delete a peer's shard file
        self._barrier(f"ckpt-clean-{step}")
        os.makedirs(tmp, exist_ok=True)
        shard = os.path.join(tmp, f"host-{self._host}.ckpt")
        faultpoint("checkpoint.shard_write", path=shard, step=step)
        # the manifest must describe the INTENDED bytes (a write torn
        # between here and publish then no longer hashes to it), but
        # materializing pickle.dumps() in RAM would double peak host
        # memory at the worst moment (the emergency preemption save of a
        # multi-GB state) — so hash/count in-line as pickle streams out
        with open(shard, "wb") as f:
            writer = _HashingWriter(f)
            pickle.dump(payload, writer, protocol=4)
            f.flush()
            os.fsync(f.fileno())  # durable before the barrier says "written"
        with open(os.path.join(tmp, self._manifest_name(self._host)),
                  "w") as f:
            json.dump({"sha256": writer.sha.hexdigest(),
                       "nbytes": writer.nbytes,
                       "host": self._host, "step": step}, f)
            f.flush()
            os.fsync(f.fileno())
        faultpoint("checkpoint.shard_file", path=shard, step=step)
        # every host's shard file must be durably in tmp before host 0
        # publishes (renames + DONE)
        self._barrier(f"ckpt-written-{step}")
        if self._host == 0:
            self._verify_shards_before_publish(tmp, final)
            faultpoint("checkpoint.publish", path=final, step=step)
            os.replace(tmp, final)
            with open(os.path.join(final, "DONE"), "w") as f:
                f.write(str(self._nhosts))
            self._retain()
        # recorded only for a COMPLETED save: an injected/real failure
        # above propagates without polluting the duration histogram
        _metrics.histogram("checkpoint.write_seconds").observe(
            time.perf_counter() - t0)
        _metrics.histogram("checkpoint.write_bytes").observe(writer.nbytes)

    def _verify_shards_before_publish(self, tmp: str, final: str):
        """Host 0, pre-DONE: every peer shard must be present in the SHARED
        directory AND match its manifest's size.  Catches both a
        per-host-local-disk misconfiguration and a torn shard write at save
        time instead of at restore — a checkpoint that fails here is never
        published.  open() (not os.path.exists) + retry with backoff: NFS
        negative dentry caching can report a peer's just-written file
        absent within the attribute-cache window."""
        def stat_visible(path):
            def attempt():
                with open(path, "rb"):
                    return os.path.getsize(path)
            try:
                return _retry.retry_call(
                    attempt, retry_on=OSError, tries=8, base_delay=0.05,
                    max_delay=1.0, deadline=5.0,
                    name="checkpoint.shard_visible")
            except _retry.RetryError:
                return None

        missing, torn = [], []
        for i in range(self._nhosts):
            size = stat_visible(os.path.join(tmp, f"host-{i}.ckpt"))
            if size is None:
                missing.append(i)
                continue
            try:
                with open(os.path.join(tmp, self._manifest_name(i))) as f:
                    want = int(json.load(f)["nbytes"])
            except (OSError, ValueError, KeyError):
                missing.append(i)  # no readable manifest: not verifiable
                continue
            if size != want:
                torn.append((i, size, want))
        if missing or torn:
            raise CheckpointWriteError(
                "checkpoint %s NOT published: %s%s — the checkpoint "
                "directory must be one shared filesystem and every shard "
                "write must complete"
                % (final,
                   ("shard/manifest files for hosts %r absent after the "
                    "write barrier" % missing) if missing else "",
                   ("; torn shard writes %s (host, bytes-on-disk, "
                    "bytes-expected)" % torn) if torn else ""))

    def _barrier(self, tag):
        if self._nhosts > 1:
            # a failed barrier must fail the save — publishing DONE without
            # it risks a checkpoint missing peer shards
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(tag)

    def _retain(self):
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep] if self.max_to_keep else []:
            shutil.rmtree(os.path.join(self.directory, f"ckpt-{s}"),
                          ignore_errors=True)

    def wait(self):
        """Block until all queued saves are on disk."""
        self._q.join()
        if self._err is not None:
            err, self._err = self._err, None
            raise RuntimeError("async checkpoint failed") from err

    #: total budget for close(): generous for a healthy-but-slow flush of
    #: the (maxsize-2) queue, but a hard bound — a wedged NFS write must
    #: not stall interpreter exit forever (the reason the writer is a
    #: daemon thread in the first place)
    _CLOSE_TIMEOUT = 600.0

    def close(self):
        """Flush queued saves and shut the writer down, bounded by
        ``_CLOSE_TIMEOUT`` total.  Idempotent; called automatically at
        interpreter exit for every live manager, so an async ``save()``
        immediately followed by process exit still lands on disk.  Raises
        if a queued save failed during the flush; warns (stderr) if the
        flush could not complete inside the budget."""
        if self._closed:
            return
        self._closed = True
        deadline = time.monotonic() + self._CLOSE_TIMEOUT
        worker, self._worker = self._worker, None
        if worker is not None and worker.is_alive():
            try:
                self._q.put(_STOP, timeout=max(
                    0.0, deadline - time.monotonic()))
            except queue.Full:
                pass  # wedged/busy writer: fall through to the drainer
            worker.join(timeout=max(0.0, deadline - time.monotonic()))
        # anything the worker did not get to (it was never started, died,
        # or the join timed out) is drained on a FRESH daemon thread with
        # a bounded join — _write on a wedged filesystem can block
        # indefinitely, and close() (atexit!) must not
        drainer = threading.Thread(target=self._drain_remaining,
                                   daemon=True, name="checkpoint-drain")
        drainer.start()
        drainer.join(timeout=max(0.1, deadline - time.monotonic()))
        if drainer.is_alive() or (worker is not None and worker.is_alive()):
            sys.stderr.write(
                "[checkpoint] close(%r) exceeded its %.0fs budget with "
                "~%d save(s) unflushed — the filesystem is wedged; those "
                "checkpoints are lost (older complete checkpoints remain "
                "restorable)\n"
                % (self.directory, self._CLOSE_TIMEOUT, self._q.qsize()))
            sys.stderr.flush()
        if self._err is not None:
            err, self._err = self._err, None
            raise RuntimeError(
                "async checkpoint failed during close") from err

    def _drain_remaining(self):
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            try:
                if item is not _STOP and item is not None:
                    step, payload = item
                    self._write(step, payload)
            except BaseException as e:
                self._err = e
            finally:
                self._q.task_done()

    # -- restore ------------------------------------------------------------
    def all_steps(self):
        out = []
        for name in os.listdir(self.directory):
            m = self._STEP_RE.match(name)
            if m and os.path.exists(
                    os.path.join(self.directory, name, "DONE")):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, step: Optional[int] = None, template: Any = None,
                fallback: Optional[bool] = None):
        """Load checkpoint ``step`` (default: newest complete).  ``template``
        — a like-shaped pytree whose jax.Array leaves carry target shardings
        — re-places restored arrays onto those shardings.

        ``fallback`` (default: True when ``step`` is None, False when a
        step is named): on a corrupt/torn/unpicklable checkpoint, warn
        loudly (:class:`CheckpointFallbackWarning`) and try the next-older
        complete checkpoint instead of raising on the first bad one.  Only
        :class:`NoUsableCheckpointError` escapes a fallback-enabled
        restore with candidates, and it names every failure."""
        from ...observability import registry as _metrics
        t0 = time.perf_counter()
        if step is None:
            candidates = list(reversed(self.all_steps()))
            if fallback is None:
                fallback = True
        else:
            candidates = [step]
            if fallback is None:
                fallback = False
        if not candidates:
            raise NoUsableCheckpointError(
                f"no complete checkpoint in {self.directory}")
        merged, failures = None, []
        for s in candidates:
            try:
                merged = self._read_step(s)
                break
            except Exception as e:
                if not fallback:
                    raise
                failures.append((s, e))
                warnings.warn(
                    "checkpoint ckpt-%d in %s is unusable (%s: %s) — "
                    "falling back to an older checkpoint"
                    % (s, self.directory, type(e).__name__, e),
                    CheckpointFallbackWarning, stacklevel=2)
        if merged is None:
            raise NoUsableCheckpointError(
                "no usable checkpoint in %s — every candidate failed: %s"
                % (self.directory,
                   "; ".join("ckpt-%d: %s: %s" % (s, type(e).__name__, e)
                             for s, e in failures)))
        tmpl = _to_template(template) if template is not None else None
        # HBM-ledger transient (ISSUE 11): between read and device
        # placement the whole deserialized tree lives host-side — the
        # restore-time memory spike an OOM post-mortem wants named.
        # Gauge set for the placement's duration, zeroed after.
        from ...observability import hbm as _hbm
        _hbm.note_restore(_host_tree_bytes(merged))
        try:
            out = _from_host(merged, tmpl)
        finally:
            _hbm.clear_restore()
        _metrics.histogram("checkpoint.restore_seconds").observe(
            time.perf_counter() - t0)
        return out

    def _read_step(self, step: int):
        """Read + integrity-verify + merge one checkpoint's shard files.
        Raises :class:`CheckpointCorruptionError` on any manifest mismatch
        or unpicklable payload; transient read errors are retried."""
        d = os.path.join(self.directory, f"ckpt-{step}")
        with open(os.path.join(d, "DONE")) as f:
            expected_hosts = int(f.read().strip() or 1)
        merged = None
        n_files = 0
        for name in sorted(os.listdir(d)):
            if not name.endswith(".ckpt"):
                continue
            n_files += 1
            path = os.path.join(d, name)
            faultpoint("checkpoint.restore_read", path=path, step=step)

            def read_bytes(p=path):
                with open(p, "rb") as f:
                    return f.read()

            blob = _retry.retry_call(read_bytes, retry_on=_retry.transient,
                                     tries=4, base_delay=0.05,
                                     name="checkpoint.restore_read")
            self._verify_blob(d, name, blob)
            try:
                part = pickle.loads(blob)
            except Exception as e:
                raise CheckpointCorruptionError(
                    "checkpoint shard %s/%s is unpicklable: %r"
                    % (d, name, e)) from e
            merged = part if merged is None else _merge_shards(merged, part)
        if merged is None:
            raise NoUsableCheckpointError(
                f"checkpoint {d} has no payload files")
        if n_files != expected_hosts:
            raise CheckpointCorruptionError(
                f"checkpoint {d} has {n_files} host files but was written "
                f"by {expected_hosts} hosts — incomplete or corrupted")
        return merged

    @staticmethod
    def _verify_blob(d: str, name: str, blob: bytes):
        """Check shard bytes against the sha256 manifest written at save
        time.  Checkpoints from before the manifest era verify vacuously
        (restore stays backward-compatible); a manifest that exists but
        does not match is a hard CheckpointCorruptionError."""
        host = name[len("host-"):-len(".ckpt")] if name.startswith("host-") \
            else None
        mpath = os.path.join(d, f"host-{host}.manifest.json") if host \
            else None
        if mpath is None or not os.path.exists(mpath):
            return
        try:
            with open(mpath) as f:
                manifest = json.load(f)
            want_sha, want_n = manifest["sha256"], int(manifest["nbytes"])
        except (OSError, ValueError, KeyError) as e:
            raise CheckpointCorruptionError(
                "checkpoint manifest %s is unreadable: %r" % (mpath, e)
            ) from e
        if len(blob) != want_n:
            raise CheckpointCorruptionError(
                "checkpoint shard %s/%s is torn: %d bytes on disk, "
                "manifest recorded %d" % (d, name, len(blob), want_n))
        got_sha = hashlib.sha256(blob).hexdigest()
        if got_sha != want_sha:
            raise CheckpointCorruptionError(
                "checkpoint shard %s/%s is corrupt: sha256 %s != manifest "
                "%s" % (d, name, got_sha, want_sha))


def _merge_shards(a, b):
    if isinstance(a, _ShardedLeaf) and isinstance(b, _ShardedLeaf):
        a.shards.extend(b.shards)
        return a
    if isinstance(a, dict):
        return {k: _merge_shards(a[k], b[k]) for k in a}
    if isinstance(a, (list, tuple)):
        return type(a)(_merge_shards(x, y) for x, y in zip(a, b))
    return a


def _to_template(obj):
    if isinstance(obj, Tensor):
        return obj._array
    if isinstance(obj, dict):
        return {k: _to_template(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_template(v) for v in obj)
    return obj


# --------------------------------------------------------------------------
# resumable data stream
# --------------------------------------------------------------------------

class ResumableIterator:
    """Wraps a DataLoader (or any re-iterable) with a persisted cursor.

    The reference's auto-checkpoint "fast-forwards the data stream" on
    restore (auto_checkpoint.py:265 semantics); here the cursor is
    (epoch, batches consumed) and fast-forward skips already-consumed
    batches after calling ``set_epoch`` for deterministic shuffles."""

    def __init__(self, loader):
        self.loader = loader
        self.epoch = 0
        self.batch = 0
        self._resuming = False

    def state_dict(self) -> Dict[str, int]:
        return {"epoch": self.epoch, "batch": self.batch}

    def set_state_dict(self, state: Dict[str, int]):
        self.epoch = int(state["epoch"])
        self.batch = int(state["batch"])
        self._resuming = True

    def __iter__(self):
        sampler = getattr(self.loader, "batch_sampler", None)
        if sampler is not None and hasattr(sampler, "set_epoch"):
            sampler.set_epoch(self.epoch)
        skip = self.batch if self._resuming else 0
        self._resuming = False
        if not skip:
            self.batch = 0
        for i, b in enumerate(iter(self.loader)):
            if i < skip:
                continue
            self.batch = i + 1
            yield b
        self.epoch += 1
        self.batch = 0


# --------------------------------------------------------------------------
# auto-resume epoch range
# --------------------------------------------------------------------------

class TrainEpochRange:
    """``for epoch in TrainEpochRange(n, ...).get():`` — the reference's
    ``acp.train_epoch_range`` (auto_checkpoint.py:598): on construction,
    restores the newest checkpoint (if any) into the registered state
    holders; while iterating, snapshots them every ``save_interval``
    epochs."""

    def __init__(self, max_epoch_num: int, name: str = "default",
                 checkpoint_dir: Optional[str] = None, save_interval: int = 1,
                 max_to_keep: int = 3, preemption_guard=None):
        checkpoint_dir = checkpoint_dir or os.environ.get(
            "PADDLE_TPU_CHECKPOINT_DIR", f"./checkpoints/{name}")
        self.manager = CheckpointManager(checkpoint_dir,
                                         max_to_keep=max_to_keep)
        self.max_epoch_num = max_epoch_num
        self.save_interval = save_interval
        # preemption_guard=True installs a fresh SIGTERM/SIGUSR1 guard
        # (PADDLE_TPU_PREEMPTION_SIGNAL); a PreemptionGuard instance is
        # used as-is.  On notice, the epoch boundary drains an emergency
        # SYNCHRONOUS checkpoint and exits with PREEMPTED_RC — the rc the
        # elastic launcher treats as restart-eligible, not a crash.
        if preemption_guard is True:
            from ...robustness.preemption import PreemptionGuard
            preemption_guard = PreemptionGuard()
        self.preemption_guard = preemption_guard
        self._getters: Dict[str, Callable[[], Any]] = {}
        self._setters: Dict[str, Callable[[Any], None]] = {}
        self._start_epoch = 0

    def register(self, name: str, get_state: Callable[[], Any],
                 set_state: Callable[[Any], None]):
        """Attach a state holder (model/optimizer/scaler/iterator):
        ``get_state() -> pytree`` and ``set_state(pytree)``."""
        self._getters[name] = get_state
        self._setters[name] = set_state
        return self

    def register_train_step(self, step, iterator: Optional[
            ResumableIterator] = None):
        """Convenience: wires a jit.TrainStep (+ optional data iterator)."""
        self.register("train_step", step.state_dict, step.set_state_dict)
        if iterator is not None:
            self.register("data_iterator", iterator.state_dict,
                          iterator.set_state_dict)
        return self

    def get(self):
        from ...core import get_rng_state, set_rng_state
        # restore() WITHOUT a step: auto-resume must ride the
        # newest→older corruption fallback — naming latest_step() here
        # would pin resume to the newest checkpoint and fail the job on
        # the exact bit-rot the fallback exists to survive.  (No complete
        # checkpoint at all => fresh start; checkpoints present but ALL
        # unusable => NoUsableCheckpointError propagates — silently
        # retraining from scratch would be worse than failing.)
        payload = None
        if self.manager.latest_step() is not None:
            payload = self.manager.restore()
        if payload is not None:
            self._start_epoch = int(payload["epoch"]) + 1
            for name, setter in self._setters.items():
                if name in payload["state"]:
                    setter(payload["state"][name])
            if payload.get("rng") is not None:
                set_rng_state(payload["rng"])
        try:
            for epoch in range(self._start_epoch, self.max_epoch_num):
                yield epoch
                faultpoint("train.epoch", epoch=epoch)
                guard = self.preemption_guard
                preempted = guard is not None and guard.preempted
                if preempted or \
                        (epoch - self._start_epoch) % self.save_interval \
                        == 0 or epoch == self.max_epoch_num - 1:
                    # on preemption the save is SYNCHRONOUS (wait=True):
                    # the grace window is short and an async save queued
                    # behind a slow write could be lost with the process
                    self.manager.save(epoch, {
                        "epoch": epoch,
                        "state": {n: g() for n, g in self._getters.items()},
                        "rng": get_rng_state(),
                    }, wait=preempted)
                if preempted:
                    from ...robustness.preemption import PREEMPTED_RC
                    sys.stderr.write(
                        "[checkpoint] preemption notice: emergency "
                        "checkpoint for epoch %d drained to %s; exiting "
                        "rc=%d (restart-eligible)\n"
                        % (epoch, self.manager.directory, PREEMPTED_RC))
                    sys.stderr.flush()
                    raise SystemExit(PREEMPTED_RC)
        finally:
            # drain queued saves even if the caller breaks out early — the
            # daemon writer thread dies with the interpreter otherwise
            self.manager.wait()
