"""Fault-tolerant checkpointing (reference:
python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py — AutoCheckpointChecker:71,
TrainEpochRange:265 — and checkpoint_saver.py).

TPU-native redesign rather than a port: the unit of persistence is a JAX
pytree (params / optimizer slots / LR / RNG / data-iterator cursor), saved

* **sharded** — each host writes only its addressable shards of every
  `jax.Array` (a ZeRO-sharded slot or GSPMD-sharded param is never gathered
  to one host), with global shape/index metadata for reassembly;
* **async** — the device→host fetch is synchronous (cheap) but pickling and
  disk IO run on a background writer thread, so the training step resumes
  immediately (the analogue of the reference's save-on-another-thread HDFS
  uploads);
* **atomically** — payloads land in a ``.tmp`` directory renamed into place,
  with a ``DONE`` marker written last; a half-written checkpoint is never
  eligible for restore.

Auto-resume = ``TrainEpochRange`` (same name/shape as the reference's
``acp.train_epoch_range``): restores the newest complete checkpoint and
fast-forwards the data stream through ``ResumableIterator``.
"""
from __future__ import annotations

import os
import pickle
import queue
import re
import shutil
import threading
import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ...core.tensor import Tensor

__all__ = ["CheckpointManager", "ResumableIterator", "TrainEpochRange"]


# --------------------------------------------------------------------------
# leaf (de)serialization
# --------------------------------------------------------------------------

class _ShardedLeaf:
    """A jax.Array saved as its host-local shards + reassembly metadata."""

    def __init__(self, arr: jax.Array):
        self.shape = tuple(arr.shape)
        self.dtype = str(arr.dtype)
        self.shards = []  # [(index: tuple of (start, stop) or None, np array)]
        for s in arr.addressable_shards:
            idx = tuple(
                (0 if sl.start is None else sl.start,
                 self.shape[d] if sl.stop is None else sl.stop)
                if isinstance(sl, slice) else sl
                for d, sl in enumerate(s.index))
            self.shards.append((idx, np.asarray(s.data)))

    def assemble(self) -> np.ndarray:
        from ...core.dtype import convert_dtype
        out = np.zeros(self.shape, dtype=convert_dtype(self.dtype))
        # shards with identical indices are replicas; unique indices must
        # partition the array — zero-filling a hole would silently corrupt
        # the restored state, so coverage is validated here
        covered = 0
        seen = set()
        total = int(np.prod(self.shape)) if self.shape else 1
        for idx, data in self.shards:
            sl = tuple(slice(a, b) for a, b in idx)
            out[sl] = data
            if idx not in seen:
                seen.add(idx)
                covered += int(np.prod([b - a for a, b in idx])) if idx else 1
        if covered < total:
            raise ValueError(
                f"sharded checkpoint leaf of shape {self.shape} has only "
                f"{covered}/{total} elements ({len(self.shards)} shards) — "
                "a per-host shard file is missing or torn")
        return out


def _to_host(obj):
    """Fetch device leaves to host containers (runs on the caller thread)."""
    if isinstance(obj, Tensor):
        return _to_host(obj._array)
    if isinstance(obj, jax.Array):
        if getattr(obj, "is_fully_replicated", True) or obj.ndim == 0:
            return np.asarray(obj)
        return _ShardedLeaf(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_host(v) for v in obj)
    return obj


def _from_host(obj, template=None):
    """Rebuild arrays; with a ``template`` leaf carrying a sharding, the
    restored value is device_put back onto that sharding (so a restored
    ZeRO/GSPMD state keeps its layout)."""
    if isinstance(obj, _ShardedLeaf):
        full = obj.assemble()
        if template is not None and isinstance(template, jax.Array):
            return jax.device_put(full, template.sharding)
        return full
    if isinstance(obj, np.ndarray):
        if template is not None and isinstance(template, jax.Array):
            return jax.device_put(obj, template.sharding)
        return obj
    if isinstance(obj, dict):
        return {k: _from_host(v, template.get(k) if isinstance(template, dict)
                              else None) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        tmpl = template if isinstance(template, (list, tuple)) else \
            [None] * len(obj)
        return type(obj)(_from_host(v, t) for v, t in zip(obj, tmpl))
    return obj


# --------------------------------------------------------------------------
# manager
# --------------------------------------------------------------------------

class CheckpointManager:
    """Directory of ``ckpt-<step>`` checkpoints with async sharded save,
    atomic publish, retention, and newest-complete restore.

    Multi-host REQUIREMENT: ``directory`` must be ONE shared filesystem
    (NFS/GCS-fuse/...) visible to every host — each host writes its
    ``host-<i>.ckpt`` shard into the same ``ckpt-<step>`` directory and
    host 0 publishes the DONE marker only after verifying every expected
    shard file is present (per-host local disks would publish a checkpoint
    whose peer shards live elsewhere and only fail at restore)."""

    _STEP_RE = re.compile(r"^ckpt-(\d+)$")

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        os.makedirs(directory, exist_ok=True)
        self._host = jax.process_index()
        self._nhosts = jax.process_count()
        # bounded: save() backpressures rather than stacking full host-RAM
        # copies of the state when IO is slower than the step time
        self._q: "queue.Queue" = queue.Queue(maxsize=2)
        self._err: Optional[BaseException] = None
        self._worker: Optional[threading.Thread] = None
        if async_save:
            self._worker = threading.Thread(target=self._drain, daemon=True)
            self._worker.start()

    # -- save ---------------------------------------------------------------
    def save(self, step: int, state: Any, wait: bool = False):
        """Snapshot ``state`` (any pytree of Tensors/arrays/py data) as
        checkpoint ``step``.  Device arrays are fetched now; IO happens on
        the writer thread unless ``wait`` or ``async_save=False``."""
        if self._err is not None:
            err, self._err = self._err, None
            raise RuntimeError("previous async checkpoint failed") from err
        payload = _to_host(state)
        # multi-host publication needs device barriers (sync_global_devices);
        # those must be issued from the main thread in the same order as the
        # training step's collectives on every host — a barrier on the writer
        # thread could interleave with training collectives and deadlock the
        # pod.  So async applies single-host; multi-host saves synchronously.
        if self.async_save and not wait and self._nhosts == 1:
            self._q.put((step, payload))
        else:
            self._write(step, payload)
        if wait:
            self.wait()

    def _drain(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                continue
            step, payload = item
            try:
                self._write(step, payload)
            except BaseException as e:  # surfaced on next save()/wait()
                self._err = e
            finally:
                self._q.task_done()

    def _write(self, step: int, payload):
        final = os.path.join(self.directory, f"ckpt-{step}")
        tmp = final + ".tmp"
        if self._host == 0:
            shutil.rmtree(tmp, ignore_errors=True)
            shutil.rmtree(final, ignore_errors=True)
        # all hosts must see the cleaned tmp dir before anyone writes into
        # it — otherwise host 0's rmtree can delete a peer's shard file
        self._barrier(f"ckpt-clean-{step}")
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, f"host-{self._host}.ckpt"), "wb") as f:
            pickle.dump(payload, f, protocol=4)
        # every host's shard file must be durably in tmp before host 0
        # publishes (renames + DONE)
        self._barrier(f"ckpt-written-{step}")
        if self._host == 0:
            # verify every host's shard landed in the SHARED directory
            # before publishing — catches a per-host-local-disk
            # misconfiguration at save time instead of at restore.
            # open() (not os.path.exists) + a short retry: NFS negative
            # dentry caching can report a peer's just-written file absent
            # within the attribute-cache window
            def shard_visible(path, tries=10, delay=0.5):
                for _ in range(tries):
                    try:
                        with open(path, "rb"):
                            return True
                    except OSError:
                        time.sleep(delay)
                return False

            missing = [i for i in range(self._nhosts)
                       if not shard_visible(
                           os.path.join(tmp, f"host-{i}.ckpt"))]
            if missing:
                raise RuntimeError(
                    "checkpoint %s: shard files for hosts %r are absent "
                    "after the write barrier — the checkpoint directory "
                    "must be one shared filesystem visible to all hosts"
                    % (final, missing))
            os.replace(tmp, final)
            with open(os.path.join(final, "DONE"), "w") as f:
                f.write(str(self._nhosts))
            self._retain()

    def _barrier(self, tag):
        if self._nhosts > 1:
            # a failed barrier must fail the save — publishing DONE without
            # it risks a checkpoint missing peer shards
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(tag)

    def _retain(self):
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep] if self.max_to_keep else []:
            shutil.rmtree(os.path.join(self.directory, f"ckpt-{s}"),
                          ignore_errors=True)

    def wait(self):
        """Block until all queued saves are on disk."""
        self._q.join()
        if self._err is not None:
            err, self._err = self._err, None
            raise RuntimeError("async checkpoint failed") from err

    # -- restore ------------------------------------------------------------
    def all_steps(self):
        out = []
        for name in os.listdir(self.directory):
            m = self._STEP_RE.match(name)
            if m and os.path.exists(
                    os.path.join(self.directory, name, "DONE")):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, step: Optional[int] = None, template: Any = None):
        """Load checkpoint ``step`` (default: newest complete).  ``template``
        — a like-shaped pytree whose jax.Array leaves carry target shardings
        — re-places restored arrays onto those shardings."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no complete checkpoint in {self.directory}")
        d = os.path.join(self.directory, f"ckpt-{step}")
        with open(os.path.join(d, "DONE")) as f:
            expected_hosts = int(f.read().strip() or 1)
        merged = None
        n_files = 0
        for name in sorted(os.listdir(d)):
            if not name.endswith(".ckpt"):
                continue
            n_files += 1
            with open(os.path.join(d, name), "rb") as f:
                part = pickle.load(f)
            merged = part if merged is None else _merge_shards(merged, part)
        if merged is None:
            raise FileNotFoundError(f"checkpoint {d} has no payload files")
        if n_files != expected_hosts:
            raise ValueError(
                f"checkpoint {d} has {n_files} host files but was written "
                f"by {expected_hosts} hosts — incomplete or corrupted")
        tmpl = _to_template(template) if template is not None else None
        return _from_host(merged, tmpl)


def _merge_shards(a, b):
    if isinstance(a, _ShardedLeaf) and isinstance(b, _ShardedLeaf):
        a.shards.extend(b.shards)
        return a
    if isinstance(a, dict):
        return {k: _merge_shards(a[k], b[k]) for k in a}
    if isinstance(a, (list, tuple)):
        return type(a)(_merge_shards(x, y) for x, y in zip(a, b))
    return a


def _to_template(obj):
    if isinstance(obj, Tensor):
        return obj._array
    if isinstance(obj, dict):
        return {k: _to_template(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_template(v) for v in obj)
    return obj


# --------------------------------------------------------------------------
# resumable data stream
# --------------------------------------------------------------------------

class ResumableIterator:
    """Wraps a DataLoader (or any re-iterable) with a persisted cursor.

    The reference's auto-checkpoint "fast-forwards the data stream" on
    restore (auto_checkpoint.py:265 semantics); here the cursor is
    (epoch, batches consumed) and fast-forward skips already-consumed
    batches after calling ``set_epoch`` for deterministic shuffles."""

    def __init__(self, loader):
        self.loader = loader
        self.epoch = 0
        self.batch = 0
        self._resuming = False

    def state_dict(self) -> Dict[str, int]:
        return {"epoch": self.epoch, "batch": self.batch}

    def set_state_dict(self, state: Dict[str, int]):
        self.epoch = int(state["epoch"])
        self.batch = int(state["batch"])
        self._resuming = True

    def __iter__(self):
        sampler = getattr(self.loader, "batch_sampler", None)
        if sampler is not None and hasattr(sampler, "set_epoch"):
            sampler.set_epoch(self.epoch)
        skip = self.batch if self._resuming else 0
        self._resuming = False
        if not skip:
            self.batch = 0
        for i, b in enumerate(iter(self.loader)):
            if i < skip:
                continue
            self.batch = i + 1
            yield b
        self.epoch += 1
        self.batch = 0


# --------------------------------------------------------------------------
# auto-resume epoch range
# --------------------------------------------------------------------------

class TrainEpochRange:
    """``for epoch in TrainEpochRange(n, ...).get():`` — the reference's
    ``acp.train_epoch_range`` (auto_checkpoint.py:598): on construction,
    restores the newest checkpoint (if any) into the registered state
    holders; while iterating, snapshots them every ``save_interval``
    epochs."""

    def __init__(self, max_epoch_num: int, name: str = "default",
                 checkpoint_dir: Optional[str] = None, save_interval: int = 1,
                 max_to_keep: int = 3):
        checkpoint_dir = checkpoint_dir or os.environ.get(
            "PADDLE_TPU_CHECKPOINT_DIR", f"./checkpoints/{name}")
        self.manager = CheckpointManager(checkpoint_dir,
                                         max_to_keep=max_to_keep)
        self.max_epoch_num = max_epoch_num
        self.save_interval = save_interval
        self._getters: Dict[str, Callable[[], Any]] = {}
        self._setters: Dict[str, Callable[[Any], None]] = {}
        self._start_epoch = 0

    def register(self, name: str, get_state: Callable[[], Any],
                 set_state: Callable[[Any], None]):
        """Attach a state holder (model/optimizer/scaler/iterator):
        ``get_state() -> pytree`` and ``set_state(pytree)``."""
        self._getters[name] = get_state
        self._setters[name] = set_state
        return self

    def register_train_step(self, step, iterator: Optional[
            ResumableIterator] = None):
        """Convenience: wires a jit.TrainStep (+ optional data iterator)."""
        self.register("train_step", step.state_dict, step.set_state_dict)
        if iterator is not None:
            self.register("data_iterator", iterator.state_dict,
                          iterator.set_state_dict)
        return self

    def get(self):
        from ...core import get_rng_state, set_rng_state
        step = self.manager.latest_step()
        if step is not None:
            payload = self.manager.restore(step)
            self._start_epoch = int(payload["epoch"]) + 1
            for name, setter in self._setters.items():
                if name in payload["state"]:
                    setter(payload["state"][name])
            if payload.get("rng") is not None:
                set_rng_state(payload["rng"])
        try:
            for epoch in range(self._start_epoch, self.max_epoch_num):
                yield epoch
                if (epoch - self._start_epoch) % self.save_interval == 0 or \
                        epoch == self.max_epoch_num - 1:
                    self.manager.save(epoch, {
                        "epoch": epoch,
                        "state": {n: g() for n, g in self._getters.items()},
                        "rng": get_rng_state(),
                    })
        finally:
            # drain queued saves even if the caller breaks out early — the
            # daemon writer thread dies with the interpreter otherwise
            self.manager.wait()
