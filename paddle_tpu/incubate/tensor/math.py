"""paddle.incubate.tensor.math (reference path) — segment reductions over
jax.ops.segment_* (implementations in incubate.graph_ops)."""
from ..graph_ops import (segment_max, segment_mean, segment_min,  # noqa
                         segment_sum)

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min"]
