"""paddle.callbacks — training callbacks namespace (reference:
python/paddle/callbacks.py re-exporting hapi/callbacks.py).

Callback/ProgBarLogger/ModelCheckpoint/LRScheduler/EarlyStopping live in
paddle_tpu.hapi; ReduceLROnPlateau and VisualDL are defined here
(reference hapi/callbacks.py:1010 ReduceLROnPlateau, :743 VisualDL —
VisualDL's writer is replaced by a JSONL scalar log, visualdl itself being
a non-goal dependency)."""
from __future__ import annotations

import json
import os

from .hapi import (Callback, EarlyStopping, LRScheduler, ModelCheckpoint,
                   ProgBarLogger)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
           "LRScheduler", "EarlyStopping", "ReduceLROnPlateau",
           "DivergenceMonitor"]


class DivergenceMonitor(Callback):
    """Watch the training loss through a
    :class:`paddle_tpu.robustness.DivergenceSentinel` and roll the model's
    compiled TrainStep back to the last good snapshot when it diverges
    (NaN/Inf or a ``spike_factor``× spike over the rolling median).

    hapi integration notes: the sentinel binds lazily to
    ``model._train_step`` (built on the first train batch), and a rewind
    restores parameters/optimizer/LR/RNG state but does NOT replay data
    batches — fit() continues with the next batch, which is the right
    trade for a callback (loops that need bit-identical replay drive the
    sentinel directly, see ROBUSTNESS.md).  After ``max_rewinds`` rewinds
    the monitor stops training (``model.stop_training``): a run that keeps
    diverging needs a human, not an infinite rollback loop.
    """

    def __init__(self, monitor="loss", max_rewinds=3, **sentinel_kwargs):
        super().__init__()
        self.monitor = monitor
        self.max_rewinds = max_rewinds
        self._sentinel_kwargs = dict(sentinel_kwargs)
        self._sentinel_kwargs.setdefault("snapshot_every", 10)
        self._sentinel = None
        self._step = 0
        self.rewinds = 0

    def _current(self, logs):
        v = (logs or {}).get(self.monitor)
        if isinstance(v, (list, tuple)):
            v = v[0] if v else None
        return None if v is None else float(v)

    def on_train_batch_end(self, step, logs=None):
        from .robustness.sentinel import DivergenceSentinel

        train_step = getattr(self.model, "_train_step", None)
        value = self._current(logs)
        if train_step is None or value is None or \
                getattr(self.model, "stop_training", False):
            return
        if self._sentinel is None or self._sentinel.train_step \
                is not train_step:
            self._sentinel = DivergenceSentinel(train_step,
                                                **self._sentinel_kwargs)
        self._step += 1
        import sys

        from .robustness.sentinel import DivergenceError
        try:
            rewound = self._sentinel.observe(self._step, value) is not None
        except DivergenceError as e:
            # ring exhausted (e.g. divergence before the first snapshot):
            # a callback must stop training, not crash fit()
            sys.stderr.write("DivergenceMonitor: %s — stopping training\n"
                             % e)
            self.model.stop_training = True
            return
        if rewound:
            self.rewinds += 1
            if self.rewinds >= self.max_rewinds:
                sys.stderr.write(
                    "DivergenceMonitor: %d rewind(s) exhausted — stopping "
                    "training\n" % self.rewinds)
                self.model.stop_training = True


class ReduceLROnPlateau(Callback):
    """Reduce optimizer LR when a monitored metric stops improving
    (reference hapi/callbacks.py ReduceLROnPlateau semantics: factor,
    patience, min_delta, cooldown, min_lr)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        self.monitor = monitor
        self.factor = float(factor)
        if self.factor >= 1.0:
            raise ValueError("ReduceLROnPlateau does not support a factor "
                             ">= 1.0")
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.cooldown_counter = 0
        self.wait = 0
        self.best = float("inf") if self.mode == "min" else -float("inf")

    def _better(self, current):
        if self.mode == "min":
            return current < self.best - self.min_delta
        return current > self.best + self.min_delta

    def _current(self, logs):
        v = (logs or {}).get(self.monitor)
        if isinstance(v, (list, tuple)):
            v = v[0]
        return None if v is None else float(v)

    def on_eval_end(self, logs=None):
        current = self._current(logs)
        if current is None:
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._better(current):
            self.best = current
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None:
                    old = float(opt.get_lr())
                    new = max(old * self.factor, self.min_lr)
                    if old - new > 1e-12:
                        try:
                            opt.set_lr(new)
                        except RuntimeError:
                            return  # LRScheduler-driven: scheduler owns lr
                        if self.verbose:
                            print("ReduceLROnPlateau: reducing learning "
                                  "rate to %g." % new)
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Scalar-logging callback (reference hapi/callbacks.py VisualDL).
    The visualdl writer is a non-goal dependency; scalars are appended to
    ``<log_dir>/scalars.jsonl`` (one {"tag", "step", "value"} per line),
    which covers the callback's train/eval scalar contract."""

    def __init__(self, log_dir="./log"):
        self.log_dir = log_dir
        self.epochs = 0
        self.steps = 0
        self._path = None

    def _write(self, tag, step, value):
        if self._path is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._path = os.path.join(self.log_dir, "scalars.jsonl")
        try:
            value = float(value)
        except (TypeError, ValueError):
            return
        with open(self._path, "a") as f:
            f.write(json.dumps({"tag": tag, "step": int(step),
                                "value": value}) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self.steps += 1
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)):
                v = v[0] if v else None
            if v is not None:
                self._write("train/%s" % k, self.steps, v)

    def on_eval_end(self, logs=None):
        self.epochs += 1
        for k, v in (logs or {}).items():
            if k in ("batch_size", "steps"):
                continue
            if isinstance(v, (list, tuple)):
                v = v[0] if v else None
            if v is not None:
                self._write("eval/%s" % k, self.epochs, v)
