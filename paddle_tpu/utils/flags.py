"""Global flag registry (reference: paddle/fluid/platform/flags.cc — 50
PADDLE_DEFINE_EXPORTED flags bridged to Python via __bootstrap__ and
set_flags/get_flags, pybind/global_value_getter_setter.cc).

TPU-native: a plain dict registry with FLAGS_* environment overrides applied
at import — every registered flag is settable via env exactly as in the
reference.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_REGISTRY: Dict[str, Any] = {}


def define_flag(name: str, default, help_str: str = ""):
    env = os.environ.get("FLAGS_" + name)
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _REGISTRY[name] = value
    return value


def set_flags(flags: Dict[str, Any]):
    for k, v in flags.items():
        k = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
        _REGISTRY[k] = v


def fast_get(name: str):
    """Hot-path flag read: direct registry access, no dict building.
    Safe to cache the bound function — the registry dict is mutated in
    place by set_flags, never replaced."""
    return _REGISTRY.get(name)


def get_flags(names=None):
    if names is None:
        return dict(_REGISTRY)
    if isinstance(names, str):
        names = [names]
    out = {}
    for k in names:
        k2 = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
        out[k] = _REGISTRY.get(k2)
    return out


# -- core flags (the TPU-meaningful subset of flags.cc) ----------------------
define_flag("check_nan_inf", False,
            "check every op output for NaN/Inf (reference operator.cc:1252)")
define_flag("use_flash_attention", True, "route attention through Pallas")
define_flag("use_pallas_norm", False,
            "route layer_norm through the Pallas kernel (XLA's fused LN is "
            "already at peak; opt-in escape hatch)")
define_flag("use_pallas_ce", False,
            "route hard-label cross_entropy through the fused Pallas "
            "softmax-CE kernel (XLA's streaming path measured faster on "
            "the 345M bench; opt-in escape hatch)")
define_flag("use_pallas_lse", False,
            "compute hard-label CE's logsumexp with the one-pass streamed "
            "Pallas kernel (big tiles, online max/sum-exp2) instead of "
            "XLA's two streaming reductions — wall-clock WASH on the "
            "GPT-2 345M bench (within the +-500 tok/s tunnel noise, "
            "~-1.5 ms/step in-device; PERF.md round-4).  Default OFF for "
            "consistency with use_pallas_ce: a wash does not earn a "
            "brand-new kernel the default single-device CE path "
            "(ADVICE r4)")
define_flag("autotune", False,
            "time kernel variant/config candidates on first call per "
            "(shape, dtype, platform) key and pick the fastest "
            "(kernels/autotune.py); off = hand-tuned defaults / cached "
            "picks only.  Also settable via PADDLE_TPU_AUTOTUNE=1")
define_flag("autotune_samples", 5,
            "timing samples per autotune candidate (median is taken)")
define_flag("autotune_pin", "",
            "pin autotune candidates: 'family=variant[:k=v,...];...' — "
            "e.g. 'flash_fwd=bf16chain+iotafree:block_q=256'; wins over "
            "cache and tuning (env: PADDLE_TPU_AUTOTUNE_PIN)")
define_flag("benchmark", False, "sync after each op for timing")
define_flag("seed", 0, "global random seed")
define_flag("allocator_strategy", "xla", "memory allocator (XLA BFC)")
define_flag("tpu_matmul_precision", "default",
            "jax.default_matmul_precision for fp32 matmuls")
