"""Custom-op / extension mechanism (reference:
python/paddle/utils/cpp_extension/ — CppExtension/CUDAExtension/load — and
paddle/fluid/framework/custom_operator.cc load_op_library).

TPU-native split:
* **Pallas / JAX custom ops** (:func:`register_op`) — the analogue of
  CUDAExtension: a raw jax-array function (typically a
  ``pl.pallas_call`` kernel) registered under a name becomes a first-class
  eager op on ``paddle_tpu.ops`` (tape autograd via jax.vjp, or a hand
  written backward via ``grad_fn`` = jax.custom_vjp), usable inside jit
  traces through ``.raw`` like every built-in op.
* **C++ host extensions** (:func:`load`) — the CppExtension analogue:
  compiles C++ sources into a shared library with g++ and exposes chosen
  C-ABI symbols through ctypes.  Host-side code (IO, tokenizers, custom
  data transforms) runs on CPU; device compute belongs in Pallas.
"""
from __future__ import annotations

import ctypes
import functools
import os
import subprocess
import tempfile
from typing import Callable, Optional, Sequence

import jax

__all__ = ["register_op", "get_op", "registered_ops", "load",
           "CppExtension", "CUDAExtension", "setup"]


# ---------------------------------------------------------------------------
# Pallas / JAX custom ops
# ---------------------------------------------------------------------------

_CUSTOM_OPS = {}


def register_op(name: str, fn: Callable = None, *,
                grad_fn: Optional[Callable] = None,
                num_diff_args: Optional[int] = None,
                expose: bool = True):
    """Register ``fn(*jax_arrays) -> jax_array(s)`` as op ``name``.

    With ``grad_fn(res, grads) -> input_grads`` the op gets a hand-written
    backward via jax.custom_vjp (``fn`` must then also return residuals:
    it is wrapped so that forward output is ``fn``'s result and ``grad_fn``
    receives ``(inputs, output)`` as residuals).  Without it, autodiff
    differentiates through the implementation (works for Pallas kernels in
    interpret mode and any jnp/lax composition).

    Usable as a decorator::

        @register_op("fused_gelu")
        def fused_gelu(x):  # raw jax arrays
            return 0.5 * x * (1 + jax.lax.erf(x / 2**0.5))

    After registration: ``paddle_tpu.ops.fused_gelu`` (Tensor-level, tape
    autograd) and ``paddle_tpu.ops.fused_gelu.raw`` (trace-level).
    """
    if fn is None:
        return lambda f: register_op(name, f, grad_fn=grad_fn,
                                     num_diff_args=num_diff_args,
                                     expose=expose)
    if not name.isidentifier():
        raise ValueError(f"op name must be a Python identifier: {name!r}")

    raw = fn
    if grad_fn is not None:
        argcount = fn.__code__.co_argcount
        n = num_diff_args if num_diff_args is not None else argcount
        # trailing args beyond num_diff_args are declared non-differentiable
        # (the custom_vjp mechanism for attrs like scales/axes); grad_fn
        # must return exactly n cotangents
        nondiff = tuple(range(n, argcount))
        _cvjp = jax.custom_vjp(fn, nondiff_argnums=nondiff)

        def _fwd(*args):
            out = fn(*args)
            return out, (args, out)

        def _bwd(*call_args):
            # with nondiff_argnums, bwd receives (*nondiff_vals, res, g)
            res, g = call_args[-2], call_args[-1]
            grads = grad_fn(res, g)
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            if len(grads) != n:
                raise ValueError(
                    f"custom op {name!r}: grad_fn returned {len(grads)} "
                    f"gradients for {n} differentiable inputs")
            return tuple(grads)

        _cvjp.defvjp(_fwd, _bwd)

        @functools.wraps(fn)
        def raw_cvjp(*args):
            return _cvjp(*args)

        raw = raw_cvjp

    from ..core.dispatch import wrap_op
    op = wrap_op(raw, name=name)
    if expose:
        from .. import ops as ops_module
        # refuse to shadow a BUILT-IN op or module (re-registering one's own
        # custom op under the same name is allowed)
        if name not in _CUSTOM_OPS:
            import paddle_tpu
            if hasattr(ops_module, name) or hasattr(paddle_tpu, name):
                raise ValueError(
                    f"op {name!r} would shadow an existing paddle_tpu "
                    "attribute; pick another name or use expose=False")
        setattr(ops_module, name, op)
        import paddle_tpu
        setattr(paddle_tpu, name, op)
    _CUSTOM_OPS[name] = op
    return op


def get_op(name: str):
    """Look up a registered custom op (reference: OpInfoMap lookup)."""
    try:
        return _CUSTOM_OPS[name]
    except KeyError:
        raise KeyError(f"custom op {name!r} is not registered; "
                       f"registered: {sorted(_CUSTOM_OPS)}") from None


def registered_ops():
    return sorted(_CUSTOM_OPS)


# ---------------------------------------------------------------------------
# C++ host extensions
# ---------------------------------------------------------------------------

class CppExtension:
    """Build spec for C++ sources (reference: cpp_extension.py CppExtension).
    In the TPU build this is consumed by :func:`load`/:func:`setup`."""

    def __init__(self, sources: Sequence[str], extra_compile_args=None,
                 extra_link_args=None, include_dirs=None, name=None):
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args or [])
        self.extra_link_args = list(extra_link_args or [])
        self.include_dirs = list(include_dirs or [])
        self.name = name


def CUDAExtension(*args, **kwargs):
    raise NotImplementedError(
        "CUDAExtension has no meaning on TPU — device kernels are Pallas "
        "(see paddle_tpu.utils.cpp_extension.register_op); host-side C++ "
        "uses CppExtension/load.")


def load(name: str, sources: Sequence[str], extra_cxx_cflags=None,
         extra_ldflags=None, extra_include_paths=None, build_directory=None,
         verbose: bool = False):
    """JIT-compile C++ sources to a shared library and return the ctypes
    CDLL (reference: cpp_extension.load, which JIT-builds and imports the
    op library; custom_operator.cc load_op_library)."""
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), f"paddle_tpu_ext_{name}")
    os.makedirs(build_dir, exist_ok=True)
    so_path = os.path.join(build_dir, f"{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    stamp = os.path.join(build_dir, f"{name}.stamp")
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if not (os.path.exists(so_path) and os.path.exists(stamp)
            and os.path.getmtime(stamp) >= newest_src):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
               "-o", so_path] + srcs
        for inc in (extra_include_paths or []):
            cmd += ["-I", inc]
        cmd += list(extra_cxx_cflags or [])
        cmd += list(extra_ldflags or [])
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"building extension {name!r} failed:\n{proc.stderr}")
        with open(stamp, "w") as f:
            f.write(str(newest_src))
    return ctypes.CDLL(so_path)


def setup(name=None, ext_modules=None, **kwargs):
    """setuptools-style entry (reference: cpp_extension.setup).  Builds each
    CppExtension immediately and returns the loaded libraries keyed by
    extension name (no pip machinery in the TPU build)."""
    out = {}
    for ext in (ext_modules or []):
        ext_name = ext.name or name
        out[ext_name] = load(ext_name, ext.sources,
                             extra_cxx_cflags=ext.extra_compile_args,
                             extra_ldflags=ext.extra_link_args,
                             extra_include_paths=ext.include_dirs)
    return out
