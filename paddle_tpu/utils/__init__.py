"""Utilities (reference surface: python/paddle/utils/)."""
from __future__ import annotations

from . import cpp_extension  # noqa: F401
from . import flags  # noqa: F401
from . import unique_name  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        return None


def run_check():
    """paddle.utils.run_check equivalent: verify the accelerator works."""
    import jax
    import jax.numpy as jnp
    x = jnp.ones((128, 128), jnp.float32)
    y = (x @ x).block_until_ready()
    n = jax.device_count()
    print(f"paddle_tpu works! backend={jax.default_backend()}, devices={n}")
    return True


def deprecated(update_to="", since="", reason=""):
    def deco(fn):
        return fn
    return deco
