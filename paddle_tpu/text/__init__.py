"""paddle.text — NLP datasets + viterbi decode (reference surface:
python/paddle/text/: Imdb, Imikolov, Movielens, UCIHousing, Conll05st,
WMT14, WMT16 datasets; paddle.text.viterbi_decode landed in the same cycle).

Every dataset PARSES a user-supplied ``data_file`` in the reference's
on-disk format (aclImdb tar.gz, ml-1m.zip, conll05st tar.gz, WMT
tarballs — see each class).  Zero-egress environment: with no
``data_file`` they fall back to deterministic synthetic data with the
real field structure/cardinality, so pipelines run unchanged;
auto-download is refused loudly.
"""
from __future__ import annotations

import gzip
import os
import re
import string
import tarfile
import zipfile
from collections import Counter

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05st",
           "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode"]

_PUNCT_DELETE = string.punctuation.encode()


class Imdb(Dataset):
    """Sentiment classification: (token_ids, label) pairs.

    ``data_file`` = the aclImdb_v1.tar.gz archive (reference
    text/datasets/imdb.py format: members ``aclImdb/{train,test}/
    {pos,neg}/*.txt``; the vocabulary is built over the WHOLE corpus with
    frequency > ``cutoff``, sorted by (-freq, word), '<unk>' appended;
    docs tokenized by punctuation-strip + lower + split; pos label 0,
    neg label 1)."""

    VOCAB_SIZE = 5147

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True, synthetic_size=None):
        self.mode = mode
        if data_file is not None and os.path.exists(data_file):
            self._parse(data_file, mode, cutoff)
            return
        n = synthetic_size or (2048 if mode == "train" else 512)
        rng = np.random.RandomState(50 if mode == "train" else 51)
        lens = rng.randint(16, 200, n)
        self.docs = [rng.randint(1, self.VOCAB_SIZE, l).astype(np.int64)
                     for l in lens]
        self.labels = rng.randint(0, 2, n).astype(np.int64)
        self.word_idx = {f"w{i}": i for i in range(self.VOCAB_SIZE)}

    def _parse(self, data_file, mode, cutoff):
        # ONE decompression pass: tokenize every matching member, keep the
        # (split, part, tokens) triples, then derive vocab and the mode's
        # docs from the cache (a second/third tar scan would re-gunzip the
        # whole ~80 MB archive each time)
        rx = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        corpus = []
        with tarfile.open(data_file) as tar:
            for member in tar.getmembers():
                m = rx.match(member.name)
                if not m:
                    continue
                raw = tar.extractfile(member).read().rstrip(b"\n\r")
                corpus.append((m.group(1), m.group(2),
                               raw.translate(None, delete=_PUNCT_DELETE)
                               .lower().split()))
        freq = Counter()
        for _split, _part, doc in corpus:
            freq.update(doc)
        kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                      key=lambda wc: (-wc[1], wc[0]))
        self.word_idx = {w.decode("latin-1"): i
                         for i, (w, _c) in enumerate(kept)}
        unk = len(self.word_idx)
        self.word_idx["<unk>"] = unk
        bidx = {w: i for i, (w, _c) in enumerate(kept)}
        docs, labels = [], []
        for label, part in ((0, "pos"), (1, "neg")):
            for split, p, doc in corpus:
                if split == mode and p == part:
                    docs.append(np.asarray(
                        [bidx.get(w, unk) for w in doc], np.int64))
                    labels.append(label)
        self.docs = docs
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset (reference: text/datasets/imikolov.py)."""

    VOCAB_SIZE = 2074

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True,
                 synthetic_size=None):
        self.window_size = window_size
        if data_file is not None and os.path.exists(data_file):
            # real PTB-style corpus: one sentence per line, whitespace tokens
            from collections import Counter
            with open(data_file) as f:
                lines = [l.split() for l in f]
            freq = Counter(w for l in lines for w in l)
            vocab = [w for w, c in freq.most_common() if c >= min_word_freq]
            self.word_idx = {w: i for i, w in enumerate(vocab)}
            unk = len(self.word_idx)
            grams = []
            for l in lines:
                ids = [self.word_idx.get(w, unk) for w in l]
                for i in range(len(ids) - window_size + 1):
                    grams.append(ids[i:i + window_size])
            self.data = np.asarray(grams, np.int64) if grams else \
                np.zeros((0, window_size), np.int64)
            return
        n = synthetic_size or (4096 if mode == "train" else 1024)
        rng = np.random.RandomState(52 if mode == "train" else 53)
        self.data = rng.randint(0, self.VOCAB_SIZE,
                                (n, window_size)).astype(np.int64)
        self.word_idx = {f"w{i}": i for i in range(self.VOCAB_SIZE)}

    def __getitem__(self, idx):
        row = self.data[idx]
        return tuple(row[:-1]), row[-1]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """Rating prediction records.

    ``data_file`` = the ml-1m.zip archive (reference
    text/datasets/movielens.py format: latin-1 ``::``-separated
    ``movies.dat`` (MovieID::Title (Year)::Genre|Genre),
    ``users.dat`` (UserID::Gender::Age::Occupation::Zip),
    ``ratings.dat`` (UserID::MovieID::Rating::Timestamp); the train/test
    split draws per-rating with ``test_ratio`` under ``rand_seed``; rating
    is rescaled to ``r*2-5``; age is bucketed by the reference age
    table)."""

    AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True, synthetic_size=None):
        if data_file is not None and os.path.exists(data_file):
            self._parse(data_file, mode, test_ratio, rand_seed)
            return
        n = synthetic_size or (4096 if mode == "train" else 512)
        rng = np.random.RandomState(54 if mode == "train" else 55)
        self.samples = []
        for _ in range(n):
            self.samples.append((
                rng.randint(1, 6041, 1).astype(np.int64),
                rng.randint(0, 2, 1).astype(np.int64),
                rng.randint(0, 7, 1).astype(np.int64),
                rng.randint(0, 21, 1).astype(np.int64),
                rng.randint(1, 3953, 1).astype(np.int64),
                rng.randint(0, 18, rng.randint(1, 4)).astype(np.int64),
                rng.randint(0, 5175, rng.randint(1, 6)).astype(np.int64),
                (rng.randint(1, 6, 1) * 2.0 - 5.0).astype(np.float32)))

    def _parse(self, data_file, mode, test_ratio, rand_seed):
        year_rx = re.compile(r"^(.*)\((\d+)\)$")
        movies, users = {}, {}
        cat_set, title_words = set(), set()
        with zipfile.ZipFile(data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode("latin-1").strip() \
                        .split("::")
                    cats = cats.split("|")
                    m = year_rx.match(title)
                    title = m.group(1) if m else title
                    movies[int(mid)] = (cats, title)
                    cat_set.update(cats)
                    title_words.update(w.lower() for w in title.split())
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _zip = line.decode(
                        "latin-1").strip().split("::")
                    users[int(uid)] = (
                        0 if gender == "M" else 1,
                        self.AGE_TABLE.index(int(age)), int(job))
            self.categories_dict = {c: i for i, c in enumerate(
                sorted(cat_set))}
            self.movie_title_dict = {w: i for i, w in enumerate(
                sorted(title_words))}
            rng = np.random.RandomState(rand_seed)
            is_test = mode == "test"
            self.samples = []
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (rng.random_sample() < test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ts = line.decode(
                        "latin-1").strip().split("::")
                    uid, mid = int(uid), int(mid)
                    gender, age, job = users[uid]
                    cats, title = movies[mid]
                    self.samples.append((
                        np.asarray([uid], np.int64),
                        np.asarray([gender], np.int64),
                        np.asarray([age], np.int64),
                        np.asarray([job], np.int64),
                        np.asarray([mid], np.int64),
                        np.asarray([self.categories_dict[c] for c in cats],
                                   np.int64),
                        np.asarray([self.movie_title_dict[w.lower()]
                                    for w in title.split()], np.int64),
                        np.asarray([float(rating) * 2 - 5.0], np.float32)))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class UCIHousing(Dataset):
    """13-feature housing regression (reference: text/datasets/uci_housing.py)."""

    def __init__(self, data_file=None, mode="train", download=True,
                 synthetic_size=None):
        if data_file is not None and os.path.exists(data_file):
            # real UCI housing file: 14 whitespace-separated floats per row
            raw = np.loadtxt(data_file, dtype=np.float32)
            if raw.ndim != 2 or raw.shape[1] != 14:
                raise ValueError(
                    f"UCIHousing: expected rows of 14 floats, got shape "
                    f"{raw.shape}")
            split = int(len(raw) * 0.8)
            part = raw[:split] if mode == "train" else raw[split:]
            self.features = part[:, :13]
            self.prices = part[:, 13:14]
            return
        n = synthetic_size or (404 if mode == "train" else 102)
        rng = np.random.RandomState(56 if mode == "train" else 57)
        self.features = rng.randn(n, 13).astype(np.float32)
        w = rng.randn(13).astype(np.float32)
        self.prices = (self.features @ w +
                       rng.randn(n).astype(np.float32) * 0.1)[:, None]

    def __getitem__(self, idx):
        return self.features[idx], self.prices[idx]

    def __len__(self):
        return len(self.prices)


class Conll05st(Dataset):
    """SRL sequence-labeling records (reference: text/datasets/conll05.py)."""

    WORD_DICT = 44068
    LABEL_DICT = 59
    PRED_DICT = 3162

    UNK_IDX = 0

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 download=True, synthetic_size=None):
        if data_file is not None and os.path.exists(data_file):
            if not (word_dict_file and verb_dict_file and target_dict_file):
                raise ValueError(
                    "Conll05st: parsing needs word_dict_file, "
                    "verb_dict_file AND target_dict_file alongside "
                    "data_file (reference conll05.py contract)")
            self._parse(data_file, word_dict_file, verb_dict_file,
                        target_dict_file)
            return
        n = synthetic_size or 1024
        rng = np.random.RandomState(58)
        lens = rng.randint(5, 40, n)
        self.samples = []
        for l in lens:
            words = rng.randint(0, self.WORD_DICT, l).astype(np.int64)
            pred = rng.randint(0, self.PRED_DICT, l).astype(np.int64)
            labels = rng.randint(0, self.LABEL_DICT, l).astype(np.int64)
            self.samples.append((words, pred, labels))
        self.word_dict = {f"w{i}": i for i in range(100)}
        self.predicate_dict = {f"v{i}": i for i in range(100)}
        self.label_dict = {f"l{i}": i for i in range(self.LABEL_DICT)}

    # -- real-archive parsing (reference conll05.py formats) ---------------
    @staticmethod
    def _read_dict(path):
        with open(path) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    @staticmethod
    def _read_label_dict(path):
        """B-/I- tag pairs get consecutive ids, 'O' last (reference
        _load_label_dict)."""
        tags = set()
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line.startswith(("B-", "I-")):
                    tags.add(line[2:])
        d = {}
        for tag in sorted(tags):
            d["B-" + tag] = len(d)
            d["I-" + tag] = len(d)
        d["O"] = len(d)
        return d

    @staticmethod
    def _props_to_bio(col):
        """One predicate column of bracket props -> BIO tags."""
        out, cur, inside = [], "O", False
        for tok in col:
            if tok == "*":
                out.append("I-" + cur if inside else "O")
            elif tok == "*)":
                out.append("I-" + cur)
                inside = False
            elif "(" in tok and ")" in tok:
                cur = tok[1:tok.index("*")]
                out.append("B-" + cur)
                inside = False
            elif "(" in tok:
                cur = tok[1:tok.index("*")]
                out.append("B-" + cur)
                inside = True
            else:
                raise ValueError("unexpected props label %r" % tok)
        return out

    def _parse(self, data_file, word_dict_file, verb_dict_file,
               target_dict_file):
        self.word_dict = self._read_dict(word_dict_file)
        self.predicate_dict = self._read_dict(verb_dict_file)
        self.label_dict = self._read_label_dict(target_dict_file)
        self.samples = []
        with tarfile.open(data_file) as tar:
            words_member = props_member = None
            for m in tar.getnames():
                if m.endswith("words/test.wsj.words.gz"):
                    words_member = m
                if m.endswith("props/test.wsj.props.gz"):
                    props_member = m
            if words_member is None or props_member is None:
                raise ValueError(
                    "Conll05st: archive lacks test.wsj words/props members")
            with gzip.GzipFile(fileobj=tar.extractfile(words_member)) as wf, \
                    gzip.GzipFile(
                        fileobj=tar.extractfile(props_member)) as pf:
                sent, cols = [], []
                for wline, pline in zip(wf, pf):
                    word = wline.decode().strip()
                    props = pline.decode().strip().split()
                    if not props:           # sentence boundary
                        self._emit(sent, cols)
                        sent, cols = [], []
                    else:
                        sent.append(word)
                        cols.append(props)
                self._emit(sent, cols)

    def _emit(self, sent, cols):
        if not sent:
            return
        ncol = len(cols[0])
        columns = [[row[i] for row in cols] for i in range(ncol)]
        verbs = [v for v in columns[0] if v != "-"]
        unk = self.UNK_IDX
        for vi, col in enumerate(columns[1:]):
            bio = self._props_to_bio(col)
            word_ids = np.asarray(
                [self.word_dict.get(w, unk) for w in sent], np.int64)
            pred = verbs[vi] if vi < len(verbs) else verbs[-1]
            pred_ids = np.full(len(sent),
                               self.predicate_dict.get(pred, 0), np.int64)
            label_ids = np.asarray(
                [self.label_dict.get(t, self.label_dict["O"]) for t in bio],
                np.int64)
            self.samples.append((word_ids, pred_ids, label_ids))

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class _WMTBase(Dataset):
    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, src_dict_size, trg_dict_size, mode, lang,
                 synthetic_size):
        n = synthetic_size or (2048 if mode == "train" else 256)
        rng = np.random.RandomState(60 if mode == "train" else 61)
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size
        self.lang = lang
        # synthetic vocabularies so get_dict() works on the fallback path
        self.src_dict = {("<s>" if i == 0 else "<e>" if i == 1 else
                          "<unk>" if i == 2 else f"w{i}"): i
                         for i in range(src_dict_size)}
        self.trg_dict = {("<s>" if i == 0 else "<e>" if i == 1 else
                          "<unk>" if i == 2 else f"t{i}"): i
                         for i in range(trg_dict_size)}
        lens = rng.randint(4, 50, n)
        self.samples = []
        for l in lens:
            src = rng.randint(3, src_dict_size, l).astype(np.int64)
            trg = rng.randint(3, trg_dict_size, max(2, l + rng.randint(-3, 4))
                              ).astype(np.int64)
            self.samples.append((src, np.concatenate([[self.BOS], trg]),
                                 np.concatenate([trg, [self.EOS]])))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT14(_WMTBase):
    """reference: text/datasets/wmt14.py (en-fr).

    ``data_file`` = the wmt14 tarball: members ``*src.dict`` /
    ``*trg.dict`` (one token per line, line number = id, first
    ``dict_size`` lines) and parallel text under ``<mode>/<mode>``
    (``src\\ttrg`` per line; pairs with a side longer than 80 tokens are
    dropped in train mode).  Samples are (src_ids with <s>/<e> wrapping,
    <s>+trg_ids, trg_ids+<e>)."""

    START, END, UNK_IDX = "<s>", "<e>", 2

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True, synthetic_size=None):
        if data_file is not None and os.path.exists(data_file):
            self._parse(data_file, mode, dict_size)
            return
        super().__init__(dict_size, dict_size, mode, "en-fr", synthetic_size)

    def _parse(self, data_file, mode, dict_size):
        def to_dict(f, size):
            return {line.decode().strip(): i
                    for i, line in enumerate(f) if i < size}

        self.samples = []
        with tarfile.open(data_file) as tar:
            names = tar.getnames()
            src_dicts = [n for n in names if n.endswith("src.dict")]
            trg_dicts = [n for n in names if n.endswith("trg.dict")]
            if len(src_dicts) != 1 or len(trg_dicts) != 1:
                raise ValueError(
                    "WMT14: archive must contain exactly one src.dict and "
                    "one trg.dict member")
            self.src_dict = to_dict(tar.extractfile(src_dicts[0]), dict_size)
            self.trg_dict = to_dict(tar.extractfile(trg_dicts[0]), dict_size)
            self.src_dict_size = len(self.src_dict)
            self.trg_dict_size = len(self.trg_dict)
            want = "%s/%s" % (mode, mode)
            start_id = self.trg_dict.get(self.START, 0)
            end_id = self.trg_dict.get(self.END, 1)
            for name in (n for n in names if n.endswith(want)):
                for line in tar.extractfile(name):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src = [self.src_dict.get(w, self.UNK_IDX)
                           for w in ([self.START] + parts[0].split()
                                     + [self.END])]
                    trg = [self.trg_dict.get(w, self.UNK_IDX)
                           for w in parts[1].split()]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    self.samples.append((
                        np.asarray(src, np.int64),
                        np.asarray([start_id] + trg, np.int64),
                        np.asarray(trg + [end_id], np.int64)))


class WMT16(_WMTBase):
    """reference: text/datasets/wmt16.py (en-de).

    ``data_file`` = the wmt16 tarball with parallel text members
    ``wmt16/{train,val,test}`` (``en\\tde`` per line).  Vocabularies are
    built from ``wmt16/train`` by frequency, capped at
    ``src/trg_dict_size`` with <s>, <e>, <unk> reserved at ids 0/1/2;
    ``lang`` selects which column is the source."""

    START, END, UNK = "<s>", "<e>", "<unk>"

    def __init__(self, data_file=None, mode="train", src_dict_size=10000,
                 trg_dict_size=10000, lang="en", download=True,
                 synthetic_size=None):
        if data_file is not None and os.path.exists(data_file):
            self._parse(data_file, mode, src_dict_size, trg_dict_size, lang)
            return
        super().__init__(src_dict_size, trg_dict_size, mode, lang,
                         synthetic_size)

    @classmethod
    def _freq_to_dict(cls, freq, size):
        d = {cls.START: 0, cls.END: 1, cls.UNK: 2}
        for w, _c in freq.most_common():
            if len(d) >= size:
                break
            d[w] = len(d)
        return d

    def _parse(self, data_file, mode, src_dict_size, trg_dict_size, lang):
        self.lang = lang
        src_col = 0 if lang == "en" else 1
        with tarfile.open(data_file) as tar:
            # one pass over wmt16/train builds BOTH vocab counters
            src_freq, trg_freq = Counter(), Counter()
            train_lines = []
            for line in tar.extractfile("wmt16/train"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                src_freq.update(parts[src_col].split())
                trg_freq.update(parts[1 - src_col].split())
                train_lines.append(parts)
            self.src_dict = self._freq_to_dict(src_freq, src_dict_size)
            self.trg_dict = self._freq_to_dict(trg_freq, trg_dict_size)
            self.src_dict_size = len(self.src_dict)
            self.trg_dict_size = len(self.trg_dict)
            start_id, end_id, unk_id = 0, 1, 2
            if mode == "train":
                pairs = train_lines
            else:
                pairs = []
                for line in tar.extractfile("wmt16/%s" % mode):
                    parts = line.decode().strip().split("\t")
                    if len(parts) == 2:
                        pairs.append(parts)
        self.samples = []
        for parts in pairs:
            src = [start_id] + [self.src_dict.get(w, unk_id)
                                for w in parts[src_col].split()] + [end_id]
            trg = [self.trg_dict.get(w, unk_id)
                   for w in parts[1 - src_col].split()]
            self.samples.append((
                np.asarray(src, np.int64),
                np.asarray([start_id] + trg, np.int64),
                np.asarray(trg + [end_id], np.int64)))

    def get_dict(self, lang, reverse=False):
        # the SOURCE dict belongs to the construction-time `lang` column
        d = self.src_dict if lang == self.lang else self.trg_dict
        if reverse:
            return {i: w for w, i in d.items()}
        return d


# ---------------------------------------------------------------------------
# viterbi decode (reference: paddle.text.viterbi_decode, the CRF decode op
# paddle/fluid/operators/viterbi_decode_op.*)
# ---------------------------------------------------------------------------

def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """Batched Viterbi decode (reference: paddle.text.viterbi_decode,
    viterbi_decode_op.cc).

    potentials: (B, T, N) emission scores; transition_params: (N, N) with
    the SAME N.  With ``include_bos_eos_tag=True`` the last two tags are the
    virtual BOS/EOS tags (reference semantics): ``transition[-2, :]`` scores
    the first step, ``transition[:, -1]`` the last.  Returns
    (scores (B,), paths (B, T)).

    TPU-native: one lax.scan over time — compiled, no Python loop per step.
    """
    import jax
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    def arr(x):
        return x._array if isinstance(x, Tensor) else jnp.asarray(x)

    pots = arr(potentials).astype(jnp.float32)
    trans = arr(transition_params).astype(jnp.float32)
    b, t, n = pots.shape
    if lengths is None:
        lens = jnp.full((b,), t, jnp.int32)
    else:
        lens = arr(lengths).astype(jnp.int32)

    if trans.shape != (n, n):
        raise ValueError(
            f"transition_params must be (num_tags, num_tags) = ({n}, {n}) "
            f"matching potentials' last dim; got {tuple(trans.shape)}")
    if include_bos_eos_tag:
        # last two tags are the virtual BOS/EOS tags: row -2 scores the
        # first step, column -1 the last (same N as the potentials)
        start = trans[-2, :][None, :]
        stop = trans[:, -1][None, :]
    else:
        start = jnp.zeros((1, n), jnp.float32)
        stop = jnp.zeros((1, n), jnp.float32)

    alpha0 = pots[:, 0, :] + start

    def step(carry, inp):
        alpha, step_i = carry
        emit = inp                                # (B, N)
        # scores[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)    # (B, N)
        best_score = jnp.max(scores, axis=1) + emit
        # positions past a sequence's length keep their alpha, and their
        # backpointers become identity so the backward trace passes through
        active = (step_i < lens)[:, None]
        new_alpha = jnp.where(active, best_score, alpha)
        identity = jnp.broadcast_to(jnp.arange(n)[None, :], (b, n))
        return (new_alpha, step_i + 1), jnp.where(active, best_prev,
                                                  identity)

    (alpha, _), backptrs = jax.lax.scan(
        step, (alpha0, jnp.ones((), jnp.int32)),
        jnp.moveaxis(pots[:, 1:, :], 1, 0))
    final = alpha + stop
    scores = jnp.max(final, axis=-1)
    last_tag = jnp.argmax(final, axis=-1).astype(jnp.int32)

    def backward(carry, ptrs):
        tag = carry  # tag at time t+1 while processing backptr index t
        prev = jnp.take_along_axis(ptrs, tag[:, None], axis=1)[:, 0]
        return prev.astype(jnp.int32), tag

    # reverse scan: outputs land at their original indices, so
    # path_rev[t] = tag_{t+1}; the final carry is the time-0 tag
    first_tag, path_rev = jax.lax.scan(backward, last_tag, backptrs,
                                       reverse=True)
    paths = jnp.concatenate([first_tag[:, None],
                             jnp.moveaxis(path_rev, 0, 1)], axis=1)
    return Tensor(scores), Tensor(paths)


class ViterbiDecoder:
    """Layer-style wrapper (reference: paddle.text.ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
