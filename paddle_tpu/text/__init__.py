"""paddle.text — NLP datasets + viterbi decode (reference surface:
python/paddle/text/: Imdb, Imikolov, Movielens, UCIHousing, Conll05st,
WMT14, WMT16 datasets; paddle.text.viterbi_decode landed in the same cycle).

Zero-egress environment: like vision.datasets, every dataset falls back to
deterministic synthetic data with the real field structure/cardinality when
no source file is supplied, so pipelines run unchanged.  UCIHousing and
Imikolov parse real data files when given; the archive-format datasets
raise loudly rather than silently substituting random data for a user's
real corpus.
"""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset


def _no_parser(cls_name, data_file):
    if data_file is not None and os.path.exists(data_file):
        raise NotImplementedError(
            f"{cls_name}: parsing the original archive format is not "
            "implemented in the TPU build — refusing to silently train on "
            "synthetic data while a real corpus was supplied. Pass "
            "data_file=None to opt into the synthetic dataset.")

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05st",
           "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode"]


class Imdb(Dataset):
    """Sentiment classification: (token_ids, label) pairs
    (reference: text/datasets/imdb.py)."""

    VOCAB_SIZE = 5147

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True, synthetic_size=None):
        _no_parser("Imdb", data_file)
        self.mode = mode
        n = synthetic_size or (2048 if mode == "train" else 512)
        rng = np.random.RandomState(50 if mode == "train" else 51)
        lens = rng.randint(16, 200, n)
        self.docs = [rng.randint(1, self.VOCAB_SIZE, l).astype(np.int64)
                     for l in lens]
        self.labels = rng.randint(0, 2, n).astype(np.int64)
        self.word_idx = {f"w{i}": i for i in range(self.VOCAB_SIZE)}

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset (reference: text/datasets/imikolov.py)."""

    VOCAB_SIZE = 2074

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True,
                 synthetic_size=None):
        self.window_size = window_size
        if data_file is not None and os.path.exists(data_file):
            # real PTB-style corpus: one sentence per line, whitespace tokens
            from collections import Counter
            with open(data_file) as f:
                lines = [l.split() for l in f]
            freq = Counter(w for l in lines for w in l)
            vocab = [w for w, c in freq.most_common() if c >= min_word_freq]
            self.word_idx = {w: i for i, w in enumerate(vocab)}
            unk = len(self.word_idx)
            grams = []
            for l in lines:
                ids = [self.word_idx.get(w, unk) for w in l]
                for i in range(len(ids) - window_size + 1):
                    grams.append(ids[i:i + window_size])
            self.data = np.asarray(grams, np.int64) if grams else \
                np.zeros((0, window_size), np.int64)
            return
        n = synthetic_size or (4096 if mode == "train" else 1024)
        rng = np.random.RandomState(52 if mode == "train" else 53)
        self.data = rng.randint(0, self.VOCAB_SIZE,
                                (n, window_size)).astype(np.int64)
        self.word_idx = {f"w{i}": i for i in range(self.VOCAB_SIZE)}

    def __getitem__(self, idx):
        row = self.data[idx]
        return tuple(row[:-1]), row[-1]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """Rating prediction records (reference: text/datasets/movielens.py)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True, synthetic_size=None):
        _no_parser("Movielens", data_file)
        n = synthetic_size or (4096 if mode == "train" else 512)
        rng = np.random.RandomState(54 if mode == "train" else 55)
        self.user_id = rng.randint(1, 6041, n).astype(np.int64)
        self.gender = rng.randint(0, 2, n).astype(np.int64)
        self.age = rng.randint(0, 7, n).astype(np.int64)
        self.job = rng.randint(0, 21, n).astype(np.int64)
        self.movie_id = rng.randint(1, 3953, n).astype(np.int64)
        self.category = [rng.randint(0, 18, rng.randint(1, 4)).astype(
            np.int64) for _ in range(n)]
        self.title = [rng.randint(0, 5175, rng.randint(1, 6)).astype(
            np.int64) for _ in range(n)]
        self.rating = rng.randint(1, 6, n).astype(np.float32)

    def __getitem__(self, idx):
        return (self.user_id[idx], self.gender[idx], self.age[idx],
                self.job[idx], self.movie_id[idx], self.category[idx],
                self.title[idx], self.rating[idx])

    def __len__(self):
        return len(self.rating)


class UCIHousing(Dataset):
    """13-feature housing regression (reference: text/datasets/uci_housing.py)."""

    def __init__(self, data_file=None, mode="train", download=True,
                 synthetic_size=None):
        if data_file is not None and os.path.exists(data_file):
            # real UCI housing file: 14 whitespace-separated floats per row
            raw = np.loadtxt(data_file, dtype=np.float32)
            if raw.ndim != 2 or raw.shape[1] != 14:
                raise ValueError(
                    f"UCIHousing: expected rows of 14 floats, got shape "
                    f"{raw.shape}")
            split = int(len(raw) * 0.8)
            part = raw[:split] if mode == "train" else raw[split:]
            self.features = part[:, :13]
            self.prices = part[:, 13:14]
            return
        n = synthetic_size or (404 if mode == "train" else 102)
        rng = np.random.RandomState(56 if mode == "train" else 57)
        self.features = rng.randn(n, 13).astype(np.float32)
        w = rng.randn(13).astype(np.float32)
        self.prices = (self.features @ w +
                       rng.randn(n).astype(np.float32) * 0.1)[:, None]

    def __getitem__(self, idx):
        return self.features[idx], self.prices[idx]

    def __len__(self):
        return len(self.prices)


class Conll05st(Dataset):
    """SRL sequence-labeling records (reference: text/datasets/conll05.py)."""

    WORD_DICT = 44068
    LABEL_DICT = 59
    PRED_DICT = 3162

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 download=True, synthetic_size=None):
        _no_parser("Conll05st", data_file)
        n = synthetic_size or 1024
        rng = np.random.RandomState(58)
        lens = rng.randint(5, 40, n)
        self.samples = []
        for l in lens:
            words = rng.randint(0, self.WORD_DICT, l).astype(np.int64)
            pred = rng.randint(0, self.PRED_DICT, l).astype(np.int64)
            labels = rng.randint(0, self.LABEL_DICT, l).astype(np.int64)
            self.samples.append((words, pred, labels))

    def get_dict(self):
        return ({f"w{i}": i for i in range(100)},
                {f"v{i}": i for i in range(100)},
                {f"l{i}": i for i in range(self.LABEL_DICT)})

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class _WMTBase(Dataset):
    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, src_dict_size, trg_dict_size, mode, lang,
                 synthetic_size):
        n = synthetic_size or (2048 if mode == "train" else 256)
        rng = np.random.RandomState(60 if mode == "train" else 61)
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size
        lens = rng.randint(4, 50, n)
        self.samples = []
        for l in lens:
            src = rng.randint(3, src_dict_size, l).astype(np.int64)
            trg = rng.randint(3, trg_dict_size, max(2, l + rng.randint(-3, 4))
                              ).astype(np.int64)
            self.samples.append((src, np.concatenate([[self.BOS], trg]),
                                 np.concatenate([trg, [self.EOS]])))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT14(_WMTBase):
    """reference: text/datasets/wmt14.py (en-fr)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True, synthetic_size=None):
        _no_parser("WMT14", data_file)
        super().__init__(dict_size, dict_size, mode, "en-fr", synthetic_size)


class WMT16(_WMTBase):
    """reference: text/datasets/wmt16.py (en-de)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=10000,
                 trg_dict_size=10000, lang="en", download=True,
                 synthetic_size=None):
        _no_parser("WMT16", data_file)
        super().__init__(src_dict_size, trg_dict_size, mode, lang,
                         synthetic_size)


# ---------------------------------------------------------------------------
# viterbi decode (reference: paddle.text.viterbi_decode, the CRF decode op
# paddle/fluid/operators/viterbi_decode_op.*)
# ---------------------------------------------------------------------------

def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """Batched Viterbi decode (reference: paddle.text.viterbi_decode,
    viterbi_decode_op.cc).

    potentials: (B, T, N) emission scores; transition_params: (N, N) with
    the SAME N.  With ``include_bos_eos_tag=True`` the last two tags are the
    virtual BOS/EOS tags (reference semantics): ``transition[-2, :]`` scores
    the first step, ``transition[:, -1]`` the last.  Returns
    (scores (B,), paths (B, T)).

    TPU-native: one lax.scan over time — compiled, no Python loop per step.
    """
    import jax
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    def arr(x):
        return x._array if isinstance(x, Tensor) else jnp.asarray(x)

    pots = arr(potentials).astype(jnp.float32)
    trans = arr(transition_params).astype(jnp.float32)
    b, t, n = pots.shape
    if lengths is None:
        lens = jnp.full((b,), t, jnp.int32)
    else:
        lens = arr(lengths).astype(jnp.int32)

    if trans.shape != (n, n):
        raise ValueError(
            f"transition_params must be (num_tags, num_tags) = ({n}, {n}) "
            f"matching potentials' last dim; got {tuple(trans.shape)}")
    if include_bos_eos_tag:
        # last two tags are the virtual BOS/EOS tags: row -2 scores the
        # first step, column -1 the last (same N as the potentials)
        start = trans[-2, :][None, :]
        stop = trans[:, -1][None, :]
    else:
        start = jnp.zeros((1, n), jnp.float32)
        stop = jnp.zeros((1, n), jnp.float32)

    alpha0 = pots[:, 0, :] + start

    def step(carry, inp):
        alpha, step_i = carry
        emit = inp                                # (B, N)
        # scores[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)    # (B, N)
        best_score = jnp.max(scores, axis=1) + emit
        # positions past a sequence's length keep their alpha, and their
        # backpointers become identity so the backward trace passes through
        active = (step_i < lens)[:, None]
        new_alpha = jnp.where(active, best_score, alpha)
        identity = jnp.broadcast_to(jnp.arange(n)[None, :], (b, n))
        return (new_alpha, step_i + 1), jnp.where(active, best_prev,
                                                  identity)

    (alpha, _), backptrs = jax.lax.scan(
        step, (alpha0, jnp.ones((), jnp.int32)),
        jnp.moveaxis(pots[:, 1:, :], 1, 0))
    final = alpha + stop
    scores = jnp.max(final, axis=-1)
    last_tag = jnp.argmax(final, axis=-1).astype(jnp.int32)

    def backward(carry, ptrs):
        tag = carry  # tag at time t+1 while processing backptr index t
        prev = jnp.take_along_axis(ptrs, tag[:, None], axis=1)[:, 0]
        return prev.astype(jnp.int32), tag

    # reverse scan: outputs land at their original indices, so
    # path_rev[t] = tag_{t+1}; the final carry is the time-0 tag
    first_tag, path_rev = jax.lax.scan(backward, last_tag, backptrs,
                                       reverse=True)
    paths = jnp.concatenate([first_tag[:, None],
                             jnp.moveaxis(path_rev, 0, 1)], axis=1)
    return Tensor(scores), Tensor(paths)


class ViterbiDecoder:
    """Layer-style wrapper (reference: paddle.text.ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
