"""Static-graph facade (reference surface: python/paddle/static/).

TPU-native meaning of "static graph": a jitted + lowered XLA/StableHLO
program.  ``save_inference_model`` exports StableHLO text + weights (the
analogue of the reference's __model__ ProgramDesc + params,
static/io.py:433); ``load_inference_model`` returns an executable predictor.
"""
from __future__ import annotations

import contextlib
import os
import pickle
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..jit import StaticFunction, to_static


class InputSpec:
    """reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = list(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def _to_shape_dtype(self):
        shape = tuple(1 if (s is None or s == -1) else int(s)
                      for s in (self.shape or []))
        from ..core.dtype import convert_dtype
        return jax.ShapeDtypeStruct(shape, convert_dtype(self.dtype))


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, model=None, input_spec=None, **kwargs):
    """Export a compiled inference artifact.

    TPU-native form: StableHLO text of the jitted forward + a weights pickle.
    ``model`` (a Layer) + ``input_spec`` is the primary TPU path; the
    feed/fetch-vars signature is accepted for API parity.
    """
    if model is None:
        raise ValueError("TPU build: pass model=<Layer> and input_spec=[...]")
    from ..jit import functional_call

    state = model.functional_state()
    specs = [s._to_shape_dtype() if isinstance(s, InputSpec) else s
             for s in (input_spec or [])]
    model.eval()

    def fwd(state, *args):
        out, _ = functional_call(model, state, *args)
        return out

    lowered = jax.jit(fwd).lower(state, *specs)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".stablehlo.mlir", "w") as f:
        f.write(lowered.as_text(dialect="stablehlo"))
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({k: np.asarray(v) for k, v in state.items()}, f)
    meta = {"input_specs": [(list(s.shape), str(s.dtype)) for s in specs]}
    with open(path_prefix + ".pdmodel.meta", "wb") as f:
        pickle.dump(meta, f)
    return path_prefix


class _Predictor:
    def __init__(self, fn, state):
        self._fn = fn
        self._state = state

    def run(self, feeds):
        arrs = [f._array if isinstance(f, Tensor) else jnp.asarray(f)
                for f in feeds]
        out = self._fn(self._state, *arrs)
        return [Tensor(o) for o in jax.tree_util.tree_leaves(out)]

    def __call__(self, *feeds):
        return self.run(list(feeds))


def load_inference_model(path_prefix, model=None, executor=None, **kwargs):
    """Load the exported artifact. If the original Layer class is supplied via
    ``model``, rebuilds an executable predictor (weights + jitted forward)."""
    with open(path_prefix + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    state = {k: jnp.asarray(v) for k, v in state.items()}
    if model is not None:
        from ..jit import functional_call
        model.eval()

        @jax.jit
        def fwd(state, *args):
            out, _ = functional_call(model, state, *args)
            return out

        return _Predictor(fwd, state)
    # without the Layer, return raw artifacts (StableHLO text + weights)
    with open(path_prefix + ".stablehlo.mlir") as f:
        hlo_text = f.read()
    return hlo_text, state


@contextlib.contextmanager
def program_guard(main_program=None, startup_program=None):
    """API-compat shim: tracing replaces program construction."""
    yield


class Program:
    """API-compat shim for code that passes Program objects around."""

    def __init__(self):
        pass

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


def default_main_program():
    return Program()


def default_startup_program():
    return Program()


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class ExecutionStrategy:
    pass


class BuildStrategy:
    pass


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program


class Executor:
    """API-compat minimal executor: run(fn, feed, fetch) over jitted fns."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        raise NotImplementedError(
            "The TPU build has no ProgramDesc interpreter; use "
            "paddle_tpu.jit.to_static / TrainStep (SURVEY.md §7 table).")


# namespace parity: paddle.static.nn
class nn:
    @staticmethod
    def fc(x, size, **kw):
        raise NotImplementedError("use paddle_tpu.nn.Linear")
