"""Static-graph facade (reference surface: python/paddle/static/).

TPU-native meaning of "static graph": a jitted + lowered XLA/StableHLO
program.  ``save_inference_model`` exports StableHLO text + weights (the
analogue of the reference's __model__ ProgramDesc + params,
static/io.py:433); ``load_inference_model`` returns an executable predictor.
"""
from __future__ import annotations

import contextlib
import os
import pickle
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..jit import StaticFunction, to_static


class InputSpec:
    """reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = list(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def _to_shape_dtype(self):
        shape = tuple(1 if (s is None or s == -1) else int(s)
                      for s in (self.shape or []))
        from ..core.dtype import convert_dtype
        return jax.ShapeDtypeStruct(shape, convert_dtype(self.dtype))


def save_inference_model(path_prefix, feed_vars=None, fetch_vars=None,
                         executor=None, program=None, model=None,
                         input_spec=None, platforms=None, **kwargs):
    """Export a standalone, executable inference artifact.

    TPU-native form of the reference's __model__ ProgramDesc + params
    (static/io.py:433): a ``jax.export`` serialized StableHLO module
    (versioned, self-contained — the analogue of the versioned ProgramDesc,
    framework.proto:23) plus a weights pickle.  The artifact is executable
    WITHOUT the original Layer class (analysis_predictor.h:90 load-and-run
    contract).  StableHLO text is also written for inspection.

    ``platforms`` optionally lists lowering platforms (e.g. ("cpu", "tpu"))
    so one artifact serves both; default = current backend.
    """
    if model is None:
        raise ValueError("TPU build: pass model=<Layer> and input_spec=[...]")
    from jax import export as jexport

    from ..jit import functional_call

    state = model.functional_state()
    specs = [s._to_shape_dtype() if isinstance(s, InputSpec) else s
             for s in (input_spec or [])]
    model.eval()

    def fwd(state, *args):
        out, _ = functional_call(model, state, *args)
        return out

    jitted = jax.jit(fwd)
    exported = jexport.export(jitted, platforms=platforms)(state, *specs)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".stablehlo.mlir", "w") as f:
        f.write(exported.mlir_module())
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({k: np.asarray(v) for k, v in state.items()}, f)
    feed_names = [getattr(s, "name", None) or f"x{i}"
                  for i, s in enumerate(input_spec or [])]
    # fetch names for the Executor.run triple contract: one per flattened
    # output leaf (the analogue of the reference's fetch_vars names)
    out_shape = jax.eval_shape(jitted, state, *specs)
    n_out = len(jax.tree_util.tree_leaves(out_shape))
    fetch_names = [f"fetch_{i}" for i in range(n_out)]
    meta = {"input_specs": [(list(s.shape), str(s.dtype)) for s in specs],
            "feed_names": feed_names,
            "fetch_names": fetch_names,
            "format_version": 1}
    with open(path_prefix + ".pdmodel.meta", "wb") as f:
        pickle.dump(meta, f)
    return path_prefix


class _Predictor:
    """Executable predictor over a deserialized exported module (the
    AnalysisPredictor analogue, analysis_predictor.h:90/:132)."""

    def __init__(self, fn, state, feed_names=None, fetch_names=None):
        self._fn = fn
        self._state = state
        self.feed_names = list(feed_names or [])
        self.fetch_names = list(fetch_names or [])

    @staticmethod
    def _unwrap_feeds(feeds):
        return [f._array if isinstance(f, Tensor) else jnp.asarray(f)
                for f in feeds]

    def run(self, feeds):
        out = self._fn(self._state, *self._unwrap_feeds(feeds))
        return [Tensor(o) for o in jax.tree_util.tree_leaves(out)]

    def __call__(self, *feeds):
        return _wrap_out(self._fn(self._state, *self._unwrap_feeds(feeds)))


def _wrap_out(out):
    if isinstance(out, (list, tuple)):
        return type(out)(_wrap_out(o) for o in out)
    return Tensor(out) if hasattr(out, "dtype") else out


def load_inference_model(path_prefix, executor=None, model=None, **kwargs):
    """Load the exported artifact into an executable predictor.

    The serialized module is deserialized via ``jax.export`` and called
    directly — the original Layer class is NOT required (the reference's
    AnalysisPredictor loads and runs a ProgramDesc the same way,
    analysis_predictor.h:90).  Passing ``model`` re-traces through the live
    Layer instead (useful to re-lower for a new platform).

    With ``executor`` (positionally second, matching static/io.py:681),
    returns the reference triple ``[program, feed_names, fetch_targets]``
    for ``exe.run(program, feed=..., fetch_list=...)``.
    """
    # positional compat: a Layer in the executor slot means model=
    from ..nn.layer.layers import Layer as _Layer
    if isinstance(executor, _Layer) and model is None:
        model, executor = executor, None
    with open(path_prefix + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    state = {k: jnp.asarray(v) for k, v in state.items()}
    try:
        with open(path_prefix + ".pdmodel.meta", "rb") as f:
            meta = pickle.load(f)
        feed_names = list(meta.get("feed_names", []))
        fetch_names = list(meta.get("fetch_names", []))
    except OSError:
        feed_names, fetch_names = [], []
    if model is not None:
        from ..jit import functional_call
        model.eval()

        @jax.jit
        def fwd(state, *args):
            out, _ = functional_call(model, state, *args)
            return out

        predictor = _Predictor(fwd, state, feed_names, fetch_names)
    else:
        from jax import export as jexport
        with open(path_prefix + ".pdmodel", "rb") as f:
            exported = jexport.deserialize(bytearray(f.read()))
        predictor = _Predictor(jax.jit(exported.call), state, feed_names,
                               fetch_names)
    if executor is not None:
        # reference triple contract (static/io.py:681): the caller does
        # [prog, feeds, fetches] = load_inference_model(path, exe);
        # exe.run(prog, feed={...}, fetch_list=fetches) — fetches are the
        # REAL recorded output names, selectable individually
        return [predictor, predictor.feed_names,
                list(predictor.fetch_names)]
    return predictor


@contextlib.contextmanager
def program_guard(main_program=None, startup_program=None):
    """API-compat shim: tracing replaces program construction."""
    yield


class Program:
    """API-compat shim for code that passes Program objects around."""

    def __init__(self):
        pass

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


def default_main_program():
    return Program()


def default_startup_program():
    return Program()


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class ExecutionStrategy:
    pass


class BuildStrategy:
    pass


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program


class Executor:
    """Minimal executor facade (reference: fluid/executor.py:619).

    The TPU build has no ProgramDesc interpreter — the executable unit is a
    loaded inference predictor (jax.export module).  ``run`` supports the
    reference's load-and-run pattern::

        exe = paddle.static.Executor()
        prog, feed_names, fetches = paddle.static.load_inference_model(p, exe)
        outs = exe.run(prog, feed={name: array}, fetch_list=fetches)
    """

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if isinstance(program, _Predictor):
            names = program.feed_names
            if not names:
                if feed and len(feed) > 1:
                    # guessing an order here would silently permute inputs
                    raise ValueError(
                        "this artifact carries no feed-name metadata and "
                        "the feed has multiple entries — re-export it with "
                        "save_inference_model (names are recorded), or "
                        "call the predictor positionally")
                names = list(feed or {})
            feeds = [feed[n] for n in names] if feed else []
            outs = program.run(feeds)
            arrs = [np.asarray(o._array) for o in outs]
            if fetch_list:
                # map requested fetch names to recorded output positions
                fnames = program.fetch_names or [
                    f"fetch_{i}" for i in range(len(arrs))]
                pos = {n: i for i, n in enumerate(fnames)}
                sel = []
                for want in fetch_list:
                    name = getattr(want, "name", want)
                    if name not in pos:
                        raise KeyError(
                            "fetch %r not among this artifact's outputs %r"
                            % (name, fnames))
                    sel.append(arrs[pos[name]])
                return sel
            return arrs
        raise NotImplementedError(
            "Executor.run executes loaded inference programs; for training "
            "use paddle_tpu.jit.to_static / TrainStep (SURVEY.md §7 table).")


# namespace parity: paddle.static.nn
class nn:
    """Static-graph layer namespace.  The control-flow entries are the
    TPU-native answer to the reference's conditional_block_op.cc/while_op.cc:
    under trace they lower to lax.cond/lax.while_loop (compiled, no Python
    re-execution); eagerly they just run."""

    @staticmethod
    def fc(x, size, **kw):
        raise NotImplementedError("use paddle_tpu.nn.Linear")

    @staticmethod
    def cond(pred, true_fn=None, false_fn=None, name=None):
        import jax.lax as lax

        def _unwrap(v):
            return v._array if isinstance(v, Tensor) else v

        p = _unwrap(pred)
        t = (lambda _: _unwrap_all(true_fn())) if true_fn else (lambda _: None)
        f = (lambda _: _unwrap_all(false_fn())) if false_fn else (lambda _: None)
        out = lax.cond(jnp.asarray(p).astype(bool).reshape(()), t, f,
                       operand=None)
        return _wrap_out(out)

    @staticmethod
    def while_loop(cond, body, loop_vars, is_test=False, name=None):
        import jax.lax as lax
        init = tuple(_unwrap_all(v) for v in loop_vars)

        def c(vs):
            r = cond(*_wrap_out(list(vs)))
            r = r._array if isinstance(r, Tensor) else r
            return jnp.asarray(r).astype(bool).reshape(())

        def b(vs):
            r = body(*_wrap_out(list(vs)))
            if not isinstance(r, (list, tuple)):
                r = (r,)
            return tuple(_unwrap_all(v) for v in r)

        out = lax.while_loop(c, b, init)
        return list(_wrap_out(list(out)))

    @staticmethod
    def case(pred_fn_pairs, default=None, name=None):
        import jax.lax as lax
        preds = [p._array if isinstance(p, Tensor) else p
                 for p, _ in pred_fn_pairs]
        fns = [fn for _, fn in pred_fn_pairs]
        if default is not None:
            fns = fns + [default]
        # index of first true pred (or len(preds) for default)
        stack = jnp.stack([jnp.asarray(p).astype(bool).reshape(())
                           for p in preds])
        if default is None:
            # reference contract (layers/control_flow.py case): no match and
            # no default is an error.  Enforceable only for concrete preds;
            # traced preds fall through to the LAST branch (documented).
            try:
                if not bool(stack.any()):
                    raise ValueError(
                        "static.nn.case: no predicate matched and no "
                        "default branch was given")
            except jax.errors.TracerBoolConversionError:
                pass
        idx = jnp.where(stack.any(), jnp.argmax(stack), len(preds))
        idx = jnp.minimum(idx, len(fns) - 1)
        out = lax.switch(idx, [lambda _, f=f: _unwrap_all(f()) for f in fns],
                         None)
        return _wrap_out(out)

    @staticmethod
    def switch_case(branch_index, branch_fns, default=None, name=None):
        import jax.lax as lax
        if isinstance(branch_fns, dict):
            items = sorted(branch_fns.items())
        else:
            items = list(enumerate(branch_fns)) \
                if not isinstance(branch_fns[0], (list, tuple)) \
                else [tuple(p) for p in branch_fns]
            items.sort(key=lambda kv: kv[0])
        keys = [k for k, _ in items]
        fns = [fn for _, fn in items]
        if default is not None:
            fns = fns + [default]
        bi = branch_index._array if isinstance(branch_index, Tensor) \
            else branch_index
        bi = jnp.asarray(bi).reshape(()).astype(jnp.int32)
        if default is None:
            # reference contract: an out-of-range index without a default
            # is an error (enforceable for concrete indices only; traced
            # indices fall through to the last branch)
            try:
                if int(bi) not in keys:
                    raise ValueError(
                        "static.nn.switch_case: branch_index %d not in %r "
                        "and no default branch was given" % (int(bi), keys))
            except jax.errors.TracerIntegerConversionError:
                pass
        # map branch_index -> position in keys (default otherwise)
        pos = jnp.full((), len(fns) - 1, jnp.int32)
        for i, k in enumerate(keys):
            pos = jnp.where(bi == k, jnp.int32(i), pos)
        out = lax.switch(pos, [lambda _, f=f: _unwrap_all(f()) for f in fns],
                         None)
        return _wrap_out(out)


def _unwrap_all(tree):
    return jax.tree_util.tree_map(
        lambda l: l._array if isinstance(l, Tensor) else l, tree,
        is_leaf=lambda l: isinstance(l, Tensor))
