"""tpu-race — tier 3 of the static analysis stack: the concurrency
audit (rules TPU6xx).

Where tier 1 (tpu-lint) checks each file's AST and tier 2 (tpu-audit)
checks the traced program, this tier checks the *thread structure* of
the serving stack: a package-wide call graph (:mod:`.graph`) closed
over declared thread roots (:mod:`.roles`), with four passes
(:mod:`.rules`):

=======  ===============================================================
TPU601   blocking call reachable on the event-loop thread
TPU602   device→host sync in the decode hot loop outside the
         allowlisted fetch points (zero-syncs-per-iteration invariant)
TPU603   attribute written from ≥2 thread roles with an unlocked write
         and no declared shared_fields reason
TPU604   blocking op / second lock while holding a lock; Thread sites
         without daemon=+name= or constructed at import time
=======  ===============================================================

Run it with ``python -m paddle_tpu.analysis --concurrency --strict``.
Suppressions are the AST tier's, unchanged: inline
``# tpu-lint: disable=TPU60x`` or a reasoned entry in
``tools/tpu_lint_baseline.txt`` (TPU6xx entries are scoped to this
tier — neither other tier stale-flags them).  See ANALYSIS.md §Tier 3.
"""
from .core import ConcurrencyAnalyzer
from .graph import CallGraph, FnInfo, module_name
from .roles import DEFAULT_REGISTRY, ROLE_NAMES, RoleRegistry
from .rules import (ConcurrencyContext, ConcurrencyPass, DecodeSyncPass,
                    LoopBlockingPass, SharedStatePass, ThreadHygienePass)

CONCURRENCY_PASSES = [LoopBlockingPass, DecodeSyncPass, SharedStatePass,
                      ThreadHygienePass]
CONCURRENCY_RULES = {p.rule: p for p in CONCURRENCY_PASSES}

__all__ = [
    "CONCURRENCY_PASSES", "CONCURRENCY_RULES", "CallGraph",
    "ConcurrencyAnalyzer", "ConcurrencyContext", "ConcurrencyPass",
    "DEFAULT_REGISTRY", "DecodeSyncPass", "FnInfo", "LoopBlockingPass",
    "ROLE_NAMES", "RoleRegistry", "SharedStatePass", "ThreadHygienePass",
    "module_name",
]
