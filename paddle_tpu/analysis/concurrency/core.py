"""The concurrency-tier analyzer: contexts → call graph → role
closures → passes → :class:`~paddle_tpu.analysis.core.Report`.

Same operational discipline as the other two tiers, with the registry
as an additional input that must be *coherent* with the tree:

* an empty role registry is an **error** (exit 2), never a green run —
  an audit with no roots checks nothing;
* a registry entry whose module IS in the scanned set but whose def no
  longer exists is **drift** (error): the thread main was renamed and
  the registry line must move with it in the same PR;
* entries for modules outside the scanned paths are skipped silently,
  so targeted runs (``--concurrency paddle_tpu/serving``) stay useful —
  but if *no* root resolves at all, that is again an error;
* baseline entries are shared with ``tools/tpu_lint_baseline.txt`` and
  scoped per-tier: this analyzer loads only TPU6xx entries, so it never
  stale-flags the AST or trace tiers' lines (and vice versa).
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

from ..baseline import Baseline
from ..core import FileContext, Finding, Report, _iter_py_files, \
    fold_findings
from .graph import CallGraph
from .roles import DEFAULT_REGISTRY, RoleRegistry
from .rules import ConcurrencyContext

__all__ = ["ConcurrencyAnalyzer"]


class ConcurrencyAnalyzer:
    """Run the TPU6xx passes over a file tree."""

    def __init__(self, root: Optional[str] = None, passes=None,
                 baseline_path: Optional[str] = "auto",
                 registry: Optional[RoleRegistry] = None):
        from . import CONCURRENCY_PASSES
        self.root = os.path.abspath(root or os.getcwd())
        self.passes = [p() if isinstance(p, type) else p
                       for p in (passes if passes is not None
                                 else CONCURRENCY_PASSES)]
        self.registry = registry if registry is not None else DEFAULT_REGISTRY
        if baseline_path == "auto":
            baseline_path = os.path.join(self.root, "tools",
                                         "tpu_lint_baseline.txt")
            if not os.path.exists(baseline_path):
                baseline_path = None
        base = Baseline.load(baseline_path) if baseline_path \
            else Baseline([])
        # only this tier's entries — the AST/trace runs own the rest
        self.baseline = base.subset(lambda e: e.rule.startswith("TPU6"))

    # -- root resolution -----------------------------------------------------
    def _resolve_specs(self, graph: CallGraph, specs, label: str,
                       errors: List[str]):
        keys = set()
        for spec in specs:
            mod = spec.split(":", 1)[0]
            if mod not in graph.modules:
                continue        # targeted run: module not in scope
            key = graph.resolve_root(spec)
            if key is None:
                errors.append(
                    f"role registry drift: {label} entry '{spec}' matches "
                    f"no definition in the scanned tree — update "
                    f"analysis/concurrency/roles.py in the same change "
                    f"that moved it")
            else:
                keys.add(key)
        return keys

    def run(self, paths: Optional[Sequence[str]] = None) -> Report:
        paths = list(paths) if paths else ["paddle_tpu"]
        report = Report([], [], [], [], [])
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(self.root, p)
            if not os.path.exists(ap):
                report.errors.append(f"{p}: path does not exist")
        if self.registry.empty():
            report.errors.append(
                "concurrency role registry is empty — an audit with no "
                "thread roots checks nothing; refusing a silent green")
            return report

        contexts: List[FileContext] = []
        for path in _iter_py_files(paths, self.root):
            try:
                contexts.append(FileContext(path, self.root))
            except (SyntaxError, UnicodeDecodeError) as e:
                report.errors.append(f"{path}: {e}")
        report.files = len(contexts)

        graph = CallGraph(contexts)
        role_roots = {
            role: self._resolve_specs(graph, specs, f"role '{role}'",
                                      report.errors)
            for role, specs in self.registry.roles.items()}
        if not any(role_roots.values()) and contexts:
            report.errors.append(
                "no role roots resolved in the scanned paths — scan the "
                "package root or fix the registry; refusing a silent green")
        hot = self._resolve_specs(graph, self.registry.hot_roots,
                                  "hot_roots", report.errors)
        fetch = self._resolve_specs(graph, self.registry.fetch_allowlist,
                                    "fetch_allowlist", report.errors)
        cc = ConcurrencyContext(
            graph=graph, registry=self.registry, role_roots=role_roots,
            role_reach={role: graph.reachable(keys)
                        for role, keys in role_roots.items()},
            hot_reach=graph.reachable(hot), fetch_keys=fetch)

        raw: List[Finding] = []
        seen = set()
        for pz in self.passes:
            for f in pz.check(cc):
                if f not in seen:       # Finding is frozen/hashable
                    seen.add(f)
                    raw.append(f)
        raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        fold_findings(report, raw, contexts, self.baseline)
        return report
