"""Package-wide call graph for the concurrency tier (TPU6xx).

Where TPU101's reachability is intra-file (one ``_Graph`` per
:class:`~paddle_tpu.analysis.core.FileContext`), the concurrency rules
need the closure of *thread roots* across the whole package: the
frontend's scheduler thread calls into ``serving/scheduler.py``, the
checkpoint writer into ``observability/flight.py``, and a blocking call
three modules away still blocks the thread that reached it.

The graph is deliberately an **under-approximation** built only from
edges the AST can prove:

* ``name(...)`` — a nested def in an enclosing scope, a module-level
  function, or (via the import/alias table) a function in another
  scanned module;
* ``self.method(...)`` / ``cls.method(...)`` — resolved through the
  defining class and its recorded bases, PLUS every override in a
  scanned subclass (conservative virtual dispatch: the base
  scheduler's ``self.admit()`` reaches the disaggregated override);
* ``super().method(...)`` — the first base providing the method;
* ``module.func(...)`` / ``Class(...)`` — alias-resolved dotted names
  (a class call edges to its ``__init__`` when one is defined).

Calls through instance attributes of *other* objects
(``self.engine.prefill_step(...)``) and closures passed as callbacks
are NOT edges — cross-object thread handoff is declared in the role
registry instead (:mod:`.roles`), which is the point: the registry is
the reviewable statement of which code runs on which thread.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..core import FileContext, ScopedVisitor

__all__ = ["CallGraph", "FnInfo", "module_name"]


def module_name(relpath: str) -> str:
    """Dotted module name for a repo-relative posix path
    (``a/b/__init__.py`` -> ``a.b``)."""
    parts = relpath[:-3].split("/") if relpath.endswith(".py") \
        else relpath.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class FnInfo:
    """One function/method definition in the scanned set."""

    __slots__ = ("key", "module", "qualname", "cls", "node", "ctx", "raw")

    def __init__(self, key, module, qualname, cls, node, ctx):
        self.key = key              # "module:qualname"
        self.module = module
        self.qualname = qualname    # Finding.symbol
        self.cls = cls              # innermost enclosing class qualname
        self.node = node
        self.ctx = ctx
        self.raw: List[Tuple] = []  # unresolved call descriptors


class _ModuleWalk(ScopedVisitor):
    """Collect defs, classes (with bases) and raw call sites of one
    file into the graph's global tables."""

    def __init__(self, ctx: FileContext, module: str, g: "CallGraph"):
        super().__init__()
        self.ctx = ctx
        self.module = module
        self.g = g
        self._class_stack: List[str] = []
        self._fn_stack: List[FnInfo] = []

    # -- defs ----------------------------------------------------------------
    def visit_ClassDef(self, node):
        self._scope.append(node.name)
        qual = ".".join(self._scope)
        dotted = f"{self.module}.{qual}"
        bases = []
        for b in node.bases:
            r = self.ctx.resolve(b)
            if r:
                bases.append(r if "." in r else f"{self.module}.{r}")
        self.g.class_bases[dotted] = bases
        self._class_stack.append(qual)
        try:
            self.generic_visit(node)
        finally:
            self._class_stack.pop()
            self._scope.pop()

    def enter_function(self, node):
        qual = self.symbol
        cls = self._class_stack[-1] if self._class_stack else None
        info = FnInfo(f"{self.module}:{qual}", self.module, qual, cls,
                      node, self.ctx)
        self.g.fns[info.key] = info
        self.g.dotted[f"{self.module}.{qual}"] = info.key
        if cls is not None and qual == f"{cls}.{node.name}":
            # a direct method of the class (not a fn nested in a method)
            self.g.methods[(f"{self.module}.{cls}", node.name)] = info.key
        self._fn_stack.append(info)

    def leave_function(self, node):
        self._fn_stack.pop()

    # -- call sites ----------------------------------------------------------
    def visit_Call(self, node):
        if self._fn_stack:
            raw = self._fn_stack[-1].raw
            f = node.func
            if isinstance(f, ast.Name):
                raw.append(("local", tuple(self._scope), f.id))
                r = self.ctx.resolve(f)
                if r and "." in r:
                    raw.append(("dotted", r))
            elif isinstance(f, ast.Attribute):
                base = f.value
                if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                        and self._class_stack:
                    raw.append(("selfcall",
                                f"{self.module}.{self._class_stack[-1]}",
                                f.attr))
                elif isinstance(base, ast.Call) \
                        and self.ctx.resolve(base.func) == "super" \
                        and self._class_stack:
                    raw.append(("super",
                                f"{self.module}.{self._class_stack[-1]}",
                                f.attr))
                else:
                    r = self.ctx.resolve(f)
                    if r:
                        raw.append(("dotted", r))
        self.generic_visit(node)


class CallGraph:
    """The package-wide call graph over a set of parsed contexts."""

    def __init__(self, contexts: Sequence[FileContext]):
        self.fns: Dict[str, FnInfo] = {}
        self.dotted: Dict[str, str] = {}           # module.qualname -> key
        self.methods: Dict[Tuple[str, str], str] = {}   # (class, name) -> key
        self.class_bases: Dict[str, List[str]] = {}
        self.modules: Set[str] = set()
        self.contexts = list(contexts)
        for ctx in contexts:
            mod = module_name(ctx.relpath)
            self.modules.add(mod)
            _ModuleWalk(ctx, mod, self).visit(ctx.tree)
        self._subclasses: Dict[str, Set[str]] = {}
        for cls, bases in self.class_bases.items():
            for b in bases:
                self._subclasses.setdefault(b, set()).add(cls)
        self.edges: Dict[str, Set[str]] = {}
        for key, info in self.fns.items():
            self.edges[key] = self._resolve_calls(info)

    # -- class machinery -----------------------------------------------------
    def _mro_method(self, cls: str, name: str,
                    _seen: Optional[Set[str]] = None) -> Optional[str]:
        if (cls, name) in self.methods:
            return self.methods[(cls, name)]
        seen = _seen or set()
        seen.add(cls)
        for b in self.class_bases.get(cls, ()):
            if b not in seen:
                k = self._mro_method(b, name, seen)
                if k:
                    return k
        return None

    def _all_subclasses(self, cls: str) -> Set[str]:
        out: Set[str] = set()
        frontier = [cls]
        while frontier:
            c = frontier.pop()
            for s in self._subclasses.get(c, ()):
                if s not in out:
                    out.add(s)
                    frontier.append(s)
        return out

    def _self_call_targets(self, cls: str, name: str) -> Set[str]:
        """Conservative virtual dispatch: the method the class sees via
        its MRO plus every scanned subclass override."""
        out: Set[str] = set()
        k = self._mro_method(cls, name)
        if k:
            out.add(k)
        for sub in self._all_subclasses(cls):
            if (sub, name) in self.methods:
                out.add(self.methods[(sub, name)])
        return out

    # -- edges ---------------------------------------------------------------
    def _resolve_calls(self, info: FnInfo) -> Set[str]:
        tgts: Set[str] = set()
        for desc in info.raw:
            kind = desc[0]
            if kind == "dotted":
                d = desc[1]
                if d in self.dotted:
                    tgts.add(self.dotted[d])
                elif d in self.class_bases:
                    k = self._mro_method(d, "__init__")
                    if k:
                        tgts.add(k)
            elif kind == "local":
                _, scope, name = desc
                chain = list(scope)
                hit = None
                while chain:
                    cand = f"{info.module}:{'.'.join(chain)}.{name}"
                    if cand in self.fns:
                        hit = cand
                        break
                    chain.pop()
                if hit is None and f"{info.module}:{name}" in self.fns:
                    hit = f"{info.module}:{name}"
                if hit is not None:
                    tgts.add(hit)
                else:
                    d = f"{info.module}.{name}"
                    if d in self.class_bases:
                        k = self._mro_method(d, "__init__")
                        if k:
                            tgts.add(k)
            elif kind == "selfcall":
                _, cls, name = desc
                tgts |= self._self_call_targets(cls, name)
            elif kind == "super":
                _, cls, name = desc
                for b in self.class_bases.get(cls, ()):
                    k = self._mro_method(b, name)
                    if k:
                        tgts.add(k)
                        break
        return tgts

    # -- public API ----------------------------------------------------------
    def resolve_root(self, spec: str) -> Optional[str]:
        """``"pkg.module:Qual.name"`` -> function key, following base
        classes for inherited methods (``DisaggScheduler.step`` resolves
        to the base implementation; virtual dispatch brings the
        subclass's overrides back into the closure)."""
        if ":" not in spec:
            return None
        mod, qual = spec.split(":", 1)
        key = f"{mod}:{qual}"
        if key in self.fns:
            return key
        if "." in qual:
            cls, name = qual.rsplit(".", 1)
            cls_dotted = f"{mod}.{cls}"
            if cls_dotted in self.class_bases:
                return self._mro_method(cls_dotted, name)
        return None

    def reachable(self, roots: Iterable[str]) -> Set[str]:
        seen: Set[str] = set()
        frontier = [r for r in roots if r in self.fns]
        seen.update(frontier)
        while frontier:
            k = frontier.pop()
            for t in self.edges.get(k, ()):
                if t not in seen:
                    seen.add(t)
                    frontier.append(t)
        return seen
