"""TPU601–TPU604 — the concurrency rule passes.

Each pass consumes a :class:`ConcurrencyContext` — the package-wide
:class:`~paddle_tpu.analysis.concurrency.graph.CallGraph` plus the
role closures computed from the registry — and yields plain
:class:`~paddle_tpu.analysis.core.Finding` objects so the baseline,
inline-suppression and ``--format`` machinery of the AST tier apply
unchanged.

Shared vocabulary: the device-sync markers (``SYNC_METHODS`` /
``SYNC_CALLS`` / ``SYNC_BUILTINS``) are imported from the TPU101 pass —
one definition of "what is a sync" across tiers — with one narrowing:
TPU602 only flags ``int(x)``/``float(x)``/``bool(x)`` on a bare *Name*
(the PR-14 bug was ``int(tok)`` on a device array; ``int(task.ids.size)``
on host metadata is fine and common in the scheduler).

Known, deliberate lexical limits (documented in ANALYSIS.md):

* a ``with self._lock:`` *statement* is never itself a blocking finding
  (idiomatic bounded critical section); only explicit un-timeouted
  ``.acquire()`` calls are;
* lock scope is lexical — a helper *called* under a lock is scanned as
  unlocked (and a nested def defined under a lock runs later, so it
  correctly does NOT inherit the lock);
* anything inside an ``await`` expression is exempt from TPU601 — the
  event loop yields there (``await q.get()``,
  ``await asyncio.wait_for(q.get(), t)``, ``run_in_executor``).
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Set, Tuple

from ..core import FileContext, Finding, ScopedVisitor
from ..host_sync import SYNC_BUILTINS, SYNC_CALLS, SYNC_METHODS
from .graph import CallGraph
from .roles import RoleRegistry

__all__ = ["ConcurrencyContext", "ConcurrencyPass", "LoopBlockingPass",
           "DecodeSyncPass", "SharedStatePass", "ThreadHygienePass"]

#: zero-arg, no-timeout method calls that can park a thread forever
BLOCKING_METHODS = {"get", "wait", "join", "result", "acquire"}
#: distributed-store RPCs (blocking network I/O); matched only when the
#: receiver's resolved name ends in a segment containing "store"
STORE_OPS = {"get", "set", "add", "wait", "compare_set", "barrier"}


@dataclasses.dataclass
class ConcurrencyContext:
    """Everything a concurrency pass needs, computed once per run."""

    graph: CallGraph
    registry: RoleRegistry
    role_roots: Dict[str, Set[str]]     # role -> resolved root keys
    role_reach: Dict[str, Set[str]]     # role -> reachable closure
    hot_reach: Set[str]                 # TPU602 closure
    fetch_keys: Set[str]                # resolved fetch_allowlist
    scans: Dict[str, "_BodyScan"] = dataclasses.field(default_factory=dict)


class ConcurrencyPass:
    """Base class: one rule over the role closures."""

    rule = "TPU600"
    name = "base"
    description = ""

    def check(self, cc: ConcurrencyContext) -> Iterable[Finding]:
        return []


# ---------------------------------------------------------------------------
# shared body scanner
# ---------------------------------------------------------------------------

def _is_lock_item(item: ast.withitem) -> bool:
    """``with <expr>:`` — is <expr>'s final identifier lock-ish?
    Covers ``self._lock``, ``_LOCK``, ``self._publish_lock``."""
    e = item.context_expr
    if isinstance(e, ast.Attribute):
        name = e.attr
    elif isinstance(e, ast.Name):
        name = e.id
    else:
        return False
    return "lock" in name.lower()


def _self_fields(target) -> List[Tuple[str, ast.AST]]:
    """Fields of ``self`` written by an assignment target: plain
    ``self.x = ...`` and container stores ``self.x[k] = ...``; tuple
    unpacking recursed."""
    out: List[Tuple[str, ast.AST]] = []
    if isinstance(target, ast.Attribute) \
            and isinstance(target.value, ast.Name) \
            and target.value.id == "self":
        out.append((target.attr, target))
    elif isinstance(target, ast.Subscript):
        v = target.value
        if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
                and v.value.id == "self":
            out.append((v.attr, target))
    elif isinstance(target, (ast.Tuple, ast.List)):
        for t in target.elts:
            out.extend(_self_fields(t))
    elif isinstance(target, ast.Starred):
        out.extend(_self_fields(target.value))
    return out


class _BodyScan(ast.NodeVisitor):
    """One walk of a function body collecting calls (with await/lock
    context), self-field writes (with lock context) and with-lock
    statements (with enclosing lock depth).  Nested defs/lambdas are
    skipped — they are their own graph nodes, judged by their own
    reachability, and do not run under an enclosing lexical lock."""

    def __init__(self):
        self.calls: List[Tuple[ast.Call, bool, int]] = []
        self.writes: List[Tuple[str, ast.AST, bool]] = []
        self.lock_withs: List[Tuple[ast.AST, int]] = []
        self._await = 0
        self._locks = 0

    def scan(self, fn_node):
        for stmt in fn_node.body:
            self.visit(stmt)
        return self

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_Await(self, node):
        self._await += 1
        try:
            self.generic_visit(node)
        finally:
            self._await -= 1

    def visit_Call(self, node):
        self.calls.append((node, self._await > 0, self._locks))
        self.generic_visit(node)

    def _with(self, node):
        is_lock = any(_is_lock_item(i) for i in node.items)
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars:
                self.visit(item.optional_vars)
        if is_lock:
            self.lock_withs.append((node, self._locks))
            self._locks += 1
        try:
            for stmt in node.body:
                self.visit(stmt)
        finally:
            if is_lock:
                self._locks -= 1

    visit_With = _with
    visit_AsyncWith = _with

    def visit_Assign(self, node):
        for t in node.targets:
            for field, tn in _self_fields(t):
                self.writes.append((field, tn, self._locks > 0))
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        for field, tn in _self_fields(node.target):
            self.writes.append((field, tn, self._locks > 0))
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            for field, tn in _self_fields(node.target):
                self.writes.append((field, tn, self._locks > 0))
        self.generic_visit(node)


def _blocking_reason(ctx: FileContext, node: ast.Call):
    """Why this call can park the calling thread, or ``None``."""
    f = node.func
    q = ctx.resolve(f)
    if q == "time.sleep":
        return "time.sleep() parks the thread"
    if q == "open":
        return "file I/O (open) blocks the thread"
    if q in ("jax.block_until_ready", "jax.device_get"):
        return f"{q} blocks on the device"
    if isinstance(f, ast.Attribute):
        if f.attr == "block_until_ready" and not node.args:
            return ".block_until_ready() blocks on the device"
        base = ctx.resolve(f.value)
        if base and f.attr in STORE_OPS \
                and "store" in base.rsplit(".", 1)[-1].lower():
            return f"store op .{f.attr}() does blocking network I/O"
        if f.attr in BLOCKING_METHODS and not node.args \
                and not any(kw.arg in ("timeout", "block")
                            for kw in node.keywords if kw.arg):
            return f".{f.attr}() with no timeout can block forever"
    return None


def _scan(cc: ConcurrencyContext, key: str) -> _BodyScan:
    """Per-run memoized body scan (several passes visit the same fn)."""
    if key not in cc.scans:
        cc.scans[key] = _BodyScan().scan(cc.graph.fns[key].node)
    return cc.scans[key]


# ---------------------------------------------------------------------------
# TPU601 — blocking call reachable from the event-loop thread
# ---------------------------------------------------------------------------

class LoopBlockingPass(ConcurrencyPass):
    rule = "TPU601"
    name = "loop-blocking"
    description = ("blocking call (sleep / file or store I/O / "
                   "un-timeouted get/wait/acquire) reachable from the "
                   "event-loop thread")

    def check(self, cc: ConcurrencyContext):
        for key in sorted(cc.role_reach.get("event_loop", ())):
            info = cc.graph.fns[key]
            for call, awaited, _locks in _scan(cc, key).calls:
                if awaited:
                    continue
                reason = _blocking_reason(info.ctx, call)
                if reason:
                    yield info.ctx.finding(
                        self.rule, call,
                        f"{reason} — reachable on the event-loop thread "
                        f"(role 'event_loop'); every open stream stalls "
                        f"behind it",
                        symbol=info.qualname)


# ---------------------------------------------------------------------------
# TPU602 — device sync in the decode hot loop
# ---------------------------------------------------------------------------

class DecodeSyncPass(ConcurrencyPass):
    rule = "TPU602"
    name = "decode-sync"
    description = ("device→host sync reachable from the decode hot loop "
                   "outside the allowlisted fetch points (zero-syncs-per-"
                   "iteration invariant)")

    def check(self, cc: ConcurrencyContext):
        for key in sorted(cc.hot_reach - cc.fetch_keys):
            info = cc.graph.fns[key]
            for call, _awaited, _locks in _scan(cc, key).calls:
                f = call.func
                msg = None
                if isinstance(f, ast.Attribute) and f.attr in SYNC_METHODS \
                        and not call.args:
                    msg = f".{f.attr}() forces a device→host sync"
                else:
                    q = info.ctx.resolve(f)
                    if q in SYNC_CALLS:
                        msg = f"{q} materializes a device value on host"
                    elif isinstance(f, ast.Name) and f.id in SYNC_BUILTINS \
                            and q == f.id and len(call.args) == 1 \
                            and not call.keywords \
                            and isinstance(call.args[0], ast.Name):
                        msg = (f"{f.id}(...) on a variable concretizes it "
                               f"(host sync)")
                if msg:
                    yield info.ctx.finding(
                        self.rule, call,
                        f"{msg} — in the decode hot loop outside the "
                        f"fetch allowlist; the loop's contract is zero "
                        f"device syncs per iteration",
                        symbol=info.qualname)


# ---------------------------------------------------------------------------
# TPU603 — cross-thread shared state without a common lock
# ---------------------------------------------------------------------------

class SharedStatePass(ConcurrencyPass):
    rule = "TPU603"
    name = "shared-state"
    description = ("attribute written from ≥2 thread roles with at least "
                   "one write outside a lock and no shared_fields entry")

    def check(self, cc: ConcurrencyContext):
        # (class spec, field) -> role -> [(info, node, locked)]
        table: Dict[Tuple[str, str], Dict[str, list]] = {}
        for role, keys in cc.role_reach.items():
            for key in keys:
                info = cc.graph.fns[key]
                if info.cls is None \
                        or info.node.name in ("__init__", "__new__"):
                    # __init__ writes happen-before any thread starts
                    continue
                spec = f"{info.module}:{info.cls}"
                for field, node, locked in _scan(cc, key).writes:
                    table.setdefault((spec, field), {}) \
                        .setdefault(role, []).append((info, node, locked))
        for (spec, field), by_role in sorted(table.items()):
            if len(by_role) < 2:
                continue
            if (spec, field) in cc.registry.shared_fields:
                continue
            roles = "/".join(sorted(by_role))
            seen: Set[Tuple[str, int, int]] = set()
            for sites in by_role.values():
                for info, node, locked in sites:
                    if locked:
                        continue
                    at = (info.key, node.lineno, node.col_offset)
                    if at in seen:      # one fn can serve two roles
                        continue
                    seen.add(at)
                    yield info.ctx.finding(
                        self.rule, node,
                        f"'{field}' of {spec} is written from roles "
                        f"{roles} and this write holds no lock — guard "
                        f"it or declare (class, field) in the registry's "
                        f"shared_fields with a reason",
                        symbol=info.qualname)


# ---------------------------------------------------------------------------
# TPU604 — blocking while locked / thread hygiene
# ---------------------------------------------------------------------------

class _ThreadCtorWalk(ScopedVisitor):
    """Syntactic: every ``threading.Thread(...)`` construction site."""

    def __init__(self, ctx: FileContext):
        super().__init__()
        self.ctx = ctx
        self.findings: List[Finding] = []

    def visit_Call(self, node):
        if self.ctx.resolve(node.func) == "threading.Thread":
            kws = {kw.arg for kw in node.keywords if kw.arg}
            missing = [k for k in ("daemon", "name") if k not in kws]
            if missing:
                self.findings.append(self.ctx.finding(
                    "TPU604", node,
                    f"threading.Thread(...) without "
                    f"{' and '.join(k + '=' for k in missing)} in the "
                    f"constructor — unnamed threads break watchdog "
                    f"postmortem attribution, non-daemon threads hang "
                    f"interpreter shutdown",
                    symbol=self.symbol))
            if self.symbol == "<module>":
                self.findings.append(self.ctx.finding(
                    "TPU604", node,
                    "thread constructed at import time — it can start "
                    "before the chained threading.excepthook "
                    "(observability.flight) is installed, losing crash "
                    "postmortems",
                    symbol=self.symbol))
        self.generic_visit(node)


class ThreadHygienePass(ConcurrencyPass):
    rule = "TPU604"
    name = "thread-hygiene"
    description = ("blocking op or second lock acquired while holding a "
                   "lock; Thread(...) without daemon=/name= or built at "
                   "import time")

    def check(self, cc: ConcurrencyContext):
        for ctx in cc.graph.contexts:
            walk = _ThreadCtorWalk(ctx)
            walk.visit(ctx.tree)
            yield from walk.findings
        for key in sorted(cc.graph.fns):
            info = cc.graph.fns[key]
            scan = _scan(cc, key)
            for node, depth in scan.lock_withs:
                if depth >= 1:
                    yield info.ctx.finding(
                        self.rule, node,
                        "second lock acquired while holding one — "
                        "lock-order inversion risk; restructure or keep "
                        "a single-lock discipline",
                        symbol=info.qualname)
            for call, awaited, locks in scan.calls:
                if locks < 1 or awaited:
                    continue
                reason = _blocking_reason(info.ctx, call)
                if reason and not (isinstance(call.func, ast.Attribute)
                                   and call.func.attr == "acquire"):
                    yield info.ctx.finding(
                        self.rule, call,
                        f"{reason} while holding a lock — every thread "
                        f"contending on that lock stalls with it",
                        symbol=info.qualname)
                elif reason:
                    yield info.ctx.finding(
                        self.rule, call,
                        "explicit .acquire() of a second lock while "
                        "holding one — lock-order inversion risk",
                        symbol=info.qualname)
