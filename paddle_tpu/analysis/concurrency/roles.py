"""The declarative thread-role registry for the concurrency tier.

Every rule in this tier reasons from *roots*: functions pinned to the
thread that really runs them.  The pinning cannot be inferred — a
``threading.Thread(target=...)`` or an ``on_token=`` callback is a
runtime value the AST cannot follow — so it is DECLARED here, next to
the code it describes, and the analyzer fails loudly (exit 2) when an
entry no longer matches a definition in a scanned module: a renamed
thread main must update its registry line in the same PR, or the audit
refuses to pretend it still covers it.

Entry format: ``"pkg.module:Qual.name"`` — the module's dotted path,
a colon, and the def/class qualname exactly as tpu-lint prints it in
findings.  Inherited methods resolve through recorded base classes
(``DisaggScheduler`` entries reach the base scheduler's body, and
conservative virtual dispatch brings the overrides back in).

Roles (fixed vocabulary — a new kind of thread gets a new role here,
not an ad-hoc string at a call site):

* ``scheduler``  — the serving scheduler thread: the ONLY caller of the
  continuous-batching scheduler, plus its callbacks (``_on_token`` /
  ``_on_finish`` fire on this thread).
* ``event_loop`` — the frontend's asyncio thread: coroutines and the
  sync helpers they call.  Blocking here stalls EVERY open stream
  (rule TPU601).
* ``writer``     — background IO threads: the async checkpoint writer,
  the telemetry publisher, the store server's accept/serve threads.
* ``monitor``    — watchdog/heartbeat threads: the liveness monitor,
  the elastic heartbeat.
* ``main``       — the caller-facing API surface of each threaded
  object (start/stop/save/drain/...): whatever thread owns the object,
  as opposed to the worker threads it spawns.

``HOT_LOOP_ROOTS`` seeds rule TPU602 separately: the decode hot loop is
a *subset* of the scheduler role where the bar is stricter — zero
device syncs per iteration outside ``FETCH_ALLOWLIST`` (the invariant
PRs 7/12/14 previously proved only by timing).

``SHARED_FIELDS`` is the TPU603 allowlist: attributes deliberately
written from two roles without a common lock, each with a reason (the
TPU505 baseline-with-reasons workflow, but in code review's face rather
than a side file, because the entry documents a concurrency DESIGN, not
accepted debt).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

__all__ = ["RoleRegistry", "DEFAULT_REGISTRY", "ROLE_NAMES"]

ROLE_NAMES = ("scheduler", "event_loop", "writer", "monitor", "main")


@dataclasses.dataclass
class RoleRegistry:
    """Roles -> entry-point specs, plus the per-rule allowlists."""

    roles: Dict[str, Tuple[str, ...]]
    #: TPU602 roots — the decode hot loop (zero-sync invariant)
    hot_roots: Tuple[str, ...] = ()
    #: TPU602: functions allowed to sync, spec -> mandatory reason
    fetch_allowlist: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: TPU603: ("pkg.module:Class", "field") -> mandatory reason
    shared_fields: Dict[Tuple[str, str], str] = \
        dataclasses.field(default_factory=dict)

    def empty(self) -> bool:
        return not any(self.roles.values())


_FRONTEND = "paddle_tpu.serving.frontend"
_ROUTER = "paddle_tpu.serving.router"
_SCHED = "paddle_tpu.serving.scheduler"
_DISAGG = "paddle_tpu.serving.disagg"
_KVT = "paddle_tpu.serving.kv_tier"
_CACHE = "paddle_tpu.serving.cache"
_CKPT = "paddle_tpu.incubate.checkpoint"
_LIVE = "paddle_tpu.observability.liveness"
_AGG = "paddle_tpu.observability.aggregate"
_STORE = "paddle_tpu.distributed.store"
_ELASTIC = "paddle_tpu.distributed.fleet.elastic"

DEFAULT_REGISTRY = RoleRegistry(
    roles={
        "scheduler": (
            f"{_SCHED}:ContinuousBatchingScheduler.step",
            f"{_SCHED}:ContinuousBatchingScheduler.decode_once",
            f"{_SCHED}:ContinuousBatchingScheduler.run",
            f"{_SCHED}:ContinuousBatchingScheduler.submit",
            f"{_SCHED}:ContinuousBatchingScheduler.cancel",
            f"{_SCHED}:ContinuousBatchingScheduler.has_work",
            f"{_SCHED}:ContinuousBatchingScheduler.prefill_once",
            f"{_SCHED}:ContinuousBatchingScheduler.admit",
            f"{_DISAGG}:DisaggScheduler.admit",
            f"{_DISAGG}:DisaggScheduler.prefill_once",
            f"{_DISAGG}:DisaggScheduler.cancel",
            f"{_DISAGG}:DisaggScheduler.has_work",
            f"{_FRONTEND}:ServingFrontend._sched_main",
            f"{_FRONTEND}:ServingFrontend._on_token",
            f"{_FRONTEND}:ServingFrontend._on_finish",
            f"{_FRONTEND}:_Stream.push",
            # fleet mode (ISSUE 19): each replica thread IS a scheduler
            # thread — _loop is the sole caller of its scheduler, and
            # the router's token/finish wrappers fire on it before
            # forwarding to the frontend callbacks above
            f"{_ROUTER}:_Replica._run",
            f"{_ROUTER}:_Replica._loop",
            f"{_ROUTER}:Router._make_callbacks.on_token",
            f"{_ROUTER}:Router._make_callbacks.on_finish",
        ),
        "event_loop": (
            f"{_FRONTEND}:ServingFrontend._loop_main",
            f"{_FRONTEND}:ServingFrontend._handle",
            f"{_FRONTEND}:ServingFrontend._generate",
            f"{_FRONTEND}:ServingFrontend._stream_response",
            f"{_FRONTEND}:ServingFrontend._buffered_response",
            f"{_FRONTEND}:ServingFrontend._heartbeat",
            f"{_FRONTEND}:ServingFrontend._respond_json",
            f"{_FRONTEND}:ServingFrontend._read_request",
            f"{_FRONTEND}:ServingFrontend._cancel_stream",
            # fleet-mode admission callback: router.submit runs it on
            # the loop thread before the replica can emit a token
            f"{_FRONTEND}:ServingFrontend._generate._admitted",
        ),
        "writer": (
            f"{_CKPT}:CheckpointManager._drain",
            f"{_CKPT}:CheckpointManager._drain_remaining",
            f"{_AGG}:HostPublisher._run",
            f"{_KVT}:ClusterPrefixIndex._run",
            f"{_STORE}:_PyStoreServer._accept",
            f"{_STORE}:_PyStoreServer._serve",
        ),
        "monitor": (
            f"{_LIVE}:LivenessMonitor._run",
            f"{_ELASTIC}:ElasticManager._hb_loop",
            # the router health probe: refreshes telemetry/prefix views,
            # trips stall/death detection, respawns dead replicas
            f"{_ROUTER}:Router._probe_main",
            f"{_ROUTER}:Router.probe_once",
        ),
        "main": (
            f"{_FRONTEND}:ServingFrontend.start",
            f"{_FRONTEND}:ServingFrontend.stop",
            f"{_FRONTEND}:ServingFrontend.drain",
            f"{_FRONTEND}:ServingFrontend.wait_drained",
            f"{_ROUTER}:Router.start",
            f"{_ROUTER}:Router.stop",
            f"{_ROUTER}:Router.submit",
            f"{_ROUTER}:Router.cancel",
            f"{_ROUTER}:Router.decommission",
            f"{_CKPT}:CheckpointManager.save",
            f"{_CKPT}:CheckpointManager.wait",
            f"{_CKPT}:CheckpointManager.close",
            f"{_CKPT}:CheckpointManager.restore",
            f"{_AGG}:HostPublisher.start",
            f"{_AGG}:HostPublisher.stop",
            f"{_AGG}:HostPublisher.publish_once",
            f"{_KVT}:ClusterPrefixIndex.start",
            f"{_KVT}:ClusterPrefixIndex.stop",
            f"{_KVT}:ClusterPrefixIndex.publish_once",
            f"{_LIVE}:LivenessMonitor.start",
            f"{_LIVE}:LivenessMonitor.stop",
            f"{_LIVE}:LivenessMonitor.check_now",
            f"{_LIVE}:enable",
            f"{_LIVE}:disable",
            f"{_ELASTIC}:ElasticManager.start",
            f"{_ELASTIC}:ElasticManager.stop",
            f"{_ELASTIC}:ElasticManager.watch",
            f"{_ELASTIC}:ElasticManager.wait_for_np",
        ),
    },
    hot_roots=(
        f"{_SCHED}:ContinuousBatchingScheduler.step",
        f"{_SCHED}:ContinuousBatchingScheduler.decode_once",
        f"{_SCHED}:ContinuousBatchingScheduler.run",
    ),
    fetch_allowlist={
        f"{_SCHED}:ContinuousBatchingScheduler._consume_inflight":
            "the one allowlisted blocking fetch of an iteration "
            "(decode_fetch/decode_spec_fetch) plus the int() casts on "
            "the already-fetched host arrays",
        f"{_DISAGG}:DisaggScheduler._after_final_chunk":
            "ready-guarded first-token fetch: int(dev) runs only after "
            "dev.is_ready() returned True, so the cast never blocks the "
            "loop",
        f"{_CACHE}:np_native_view":
            "host staging primitive of the spill/handoff/host-fetch "
            "paths: asarray materializes exported KV rows once per "
            "interleaved chunk (disagg handoff or kv_tier spill/fetch), "
            "never on a decode dispatch — the chunk IS the allowlisted "
            "transfer",
    },
    shared_fields={
        (f"{_CKPT}:CheckpointManager", "_err"):
            "single-slot async-error handoff: the writer publishes the "
            "exception, save()/wait() consume-and-clear; both sides are "
            "single GIL-atomic reference swaps and a torn interleaving "
            "only defers the re-raise to the next save()",
    },
)
