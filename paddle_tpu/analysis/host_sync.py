"""TPU101 — host-sync detector.

A device→host transfer inside a traced/compiled region either fails at
trace time (``.item()`` on a tracer) or — worse — silently forces a
blocking round-trip per step when it sneaks into pre/post-processing that
later migrates under jit.  The reference build never has this problem
because its hot path is a C++ interpreter; ours is Python all the way to
the jit boundary, so the boundary must be policed.

Scope: a finding fires only for sync *markers* inside functions that are
**trace-reachable within the file**:

* decorated with jit/pjit/shard_map/vmap/grad/checkpoint (any alias);
* passed by name to a trace entry point (``jax.jit(f)``,
  ``shard_map(f, ...)``, ``jax.lax.scan(body, ...)`` — lax control flow
  traces its operands even outside jit);
* a lambda passed inline to one of those calls;
* called (by local name) from any function already reachable —
  transitive closure, intra-file only.

Markers: ``.item()`` / ``.numpy()`` / ``.tolist()`` /
``.block_until_ready()`` calls, ``np.asarray`` / ``np.array`` /
``jax.device_get``, and ``float(x)`` / ``int(x)`` / ``bool(x)`` applied
directly to a variable (constants and nested calls like
``int(np.prod(shape))`` are static at trace time and stay exempt).

Cross-module reachability is intentionally out of scope — the runtime
HLO audit (tests/test_x64_audit.py) covers whole-program properties; this
pass exists to catch regressions at review time without a compile.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import FileContext, Finding, LintPass, ScopedVisitor

RULE = "TPU101"

#: decorator / wrapper qualnames whose function arguments are traced.
TRACE_ENTRY_SUFFIXES = {
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
    "jax.experimental.shard_map.shard_map", "jax.vmap", "jax.grad",
    "jax.value_and_grad", "jax.checkpoint", "jax.remat",
    "jax.lax.scan", "jax.lax.fori_loop", "jax.lax.while_loop",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.associative_scan",
    "jax.lax.map",
}
#: bare names that count even when alias resolution fails.
TRACE_ENTRY_BARE = {"jit", "pjit", "shard_map", "to_static"}

SYNC_METHODS = {"item", "numpy", "tolist", "block_until_ready"}
SYNC_CALLS = {"numpy.asarray", "numpy.array", "jax.device_get",
              "jax.block_until_ready"}
SYNC_BUILTINS = {"float", "int", "bool"}


def _is_trace_entry(ctx: FileContext, node) -> bool:
    """Is `node` (a decorator expr or call-func expr) a trace entry?"""
    if isinstance(node, ast.Call):
        node = node.func
    q = ctx.resolve(node)
    if q is None:
        return False
    if q in TRACE_ENTRY_SUFFIXES:
        return True
    last = q.rsplit(".", 1)[-1]
    return last in TRACE_ENTRY_BARE


class _Graph(ScopedVisitor):
    """First walk: function table, local call graph, trace seeds."""

    def __init__(self, ctx: FileContext):
        super().__init__()
        self.ctx = ctx
        self.defs: Dict[str, ast.AST] = {}          # qualname -> def node
        self.by_name: Dict[str, List[str]] = {}     # bare name -> qualnames
        self.calls: Dict[str, Set[str]] = {}        # qualname -> bare names
        self.seeds: Set[str] = set()                # reachable roots
        self.seed_lambdas: List[ast.Lambda] = []    # lambdas passed to jit

    def enter_function(self, node):
        q = self.symbol
        self.defs[q] = node
        self.by_name.setdefault(node.name, []).append(q)
        self.calls.setdefault(q, set())
        for dec in node.decorator_list:
            if _is_trace_entry(self.ctx, dec):
                self.seeds.add(q)

    def visit_Call(self, node):
        sym = self.symbol
        if sym != "<module>":
            f = node.func
            if isinstance(f, ast.Name):
                self.calls.setdefault(sym, set()).add(f.id)
            elif isinstance(f, ast.Attribute):
                # self._helper(...) — bare method-name edge
                self.calls.setdefault(sym, set()).add(f.attr)
        if _is_trace_entry(self.ctx, node.func):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    self.seeds.add(arg.id)          # bare name; mapped later
                elif isinstance(arg, ast.Attribute):
                    self.seeds.add(arg.attr)        # jax.jit(self._method)
                elif isinstance(arg, ast.Lambda):
                    self.seed_lambdas.append(arg)
        self.generic_visit(node)


class _MarkerScan(ast.NodeVisitor):
    """Scan one reachable function body for sync markers, skipping nested
    defs/lambdas (they are judged by their own reachability)."""

    def __init__(self, ctx: FileContext, symbol: str, skip_nested=True):
        self.ctx = ctx
        self.symbol = symbol
        self.skip_nested = skip_nested
        self.findings: List[Finding] = []

    def visit_FunctionDef(self, node):
        if not self.skip_nested:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if not self.skip_nested:
            self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in SYNC_METHODS \
                and not node.args:
            self._flag(node, f".{f.attr}() forces a device→host sync "
                             f"inside a traced function")
        else:
            q = self.ctx.resolve(f)
            if q in SYNC_CALLS:
                self._flag(node, f"{q} materializes a traced value on "
                                 f"host")
            elif isinstance(f, ast.Name) and f.id in SYNC_BUILTINS \
                    and q == f.id and len(node.args) == 1 \
                    and not node.keywords \
                    and isinstance(node.args[0], (ast.Name, ast.Attribute)):
                self._flag(node, f"{f.id}(...) on a traced value forces "
                                 f"concretization (host sync)")
        self.generic_visit(node)

    def _flag(self, node, msg):
        self.findings.append(self.ctx.finding(RULE, node, msg, self.symbol))


class HostSyncPass(LintPass):
    rule = RULE
    name = "host-sync"
    description = ("device→host sync (.item()/np.asarray/float()/...) "
                   "reachable from a jitted function")

    def check(self, ctx: FileContext):
        g = _Graph(ctx)
        g.visit(ctx.tree)

        # seeds arrive as qualnames (decorators) or bare names (call args)
        reachable: Set[str] = set()
        frontier: List[str] = []
        for s in g.seeds:
            for q in ([s] if s in g.defs else g.by_name.get(s, [])):
                if q not in reachable:
                    reachable.add(q)
                    frontier.append(q)
        while frontier:
            q = frontier.pop()
            for callee in g.calls.get(q, ()):
                for cq in g.by_name.get(callee, []):
                    if cq not in reachable:
                        reachable.add(cq)
                        frontier.append(cq)

        findings: List[Finding] = []
        for q in sorted(reachable):
            node = g.defs[q]
            scan = _MarkerScan(ctx, q)
            for stmt in node.body:
                scan.visit(stmt)
            findings.extend(scan.findings)
        for lam in g.seed_lambdas:
            scan = _MarkerScan(ctx, "<lambda>")
            scan.visit(lam.body)
            findings.extend(scan.findings)
        return findings
