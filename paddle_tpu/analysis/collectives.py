"""TPU301 — collective axis-name checker.

On TPU a communicator is a *mesh axis name* (paddle_tpu/distributed/mesh.py
AXIS_ORDER — the NCCL ring-id registry's analogue).  A ``lax.psum`` over an
axis name that no mesh declares fails only at trace time, inside a
shard_map, usually several call layers away from the typo.  This pass
cross-references the two statically:

* **declarations** — collected in :meth:`prepare` from *every* analyzed
  file: string/tuple/dict-value assignments to names matching ``AXIS``
  (``AXIS_ORDER``, ``EP_AXIS``, ``AXIS_MAP`` values) plus the
  ``_default_axis`` registry default.
* **uses** — ``jax.lax`` collective calls (:data:`COLLECTIVES`) whose
  axis argument is a string literal, a tuple of literals, or a name that
  resolves to a module-level string constant.

A literal axis that matches no declaration anywhere in scope is flagged.
Variables that cannot be resolved statically are skipped (most library
code threads ``axis_name`` parameters — those are the *caller's*
declaration problem).  If no declarations exist in scope at all the pass
stays silent rather than flagging every axis in a partial run.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional, Sequence, Set

from .core import FileContext, Finding, LintPass, ScopedVisitor

RULE = "TPU301"

#: collective name -> positional index of its axis-name argument.
COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "all_to_all": 1, "ppermute": 1, "pshuffle": 1,
    "pbroadcast": 1, "pvary": 1, "pcast": 1,
    "axis_index": 0, "axis_size": 0,
}

_AXIS_NAME_RE = re.compile(r"(^|_)axis", re.IGNORECASE)


def _collect_strings(node) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            out.extend(_collect_strings(e))
        return out
    if isinstance(node, ast.Dict):
        out = []
        for v in node.values:
            out.extend(_collect_strings(v))
        return out
    return []


class CollectiveAxisPass(LintPass):
    rule = RULE
    name = "collective-axis"
    description = ("lax collective calls whose literal axis_name matches "
                   "no declared mesh axis")

    def __init__(self):
        self.declared: Set[str] = set()

    def prepare(self, contexts: Sequence[FileContext]):
        self.declared = set()
        for ctx in contexts:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets = [node.target]
                else:
                    continue
                for t in targets:
                    if isinstance(t, ast.Name) and (
                            _AXIS_NAME_RE.search(t.id)
                            or t.id == "_default_axis"):
                        self.declared.update(_collect_strings(node.value))

    def check(self, ctx: FileContext):
        if not self.declared:
            return []
        declared = self.declared
        findings: List[Finding] = []

        class V(ScopedVisitor):
            def visit_Call(self, vnode):
                q = ctx.resolve_call(vnode)
                if q and q.startswith("jax.lax."):
                    short = q[len("jax.lax."):]
                    if short in COLLECTIVES:
                        axis = _axis_arg(vnode, COLLECTIVES[short])
                        for name, loc in _axis_literals(ctx, axis):
                            if name not in declared:
                                findings.append(ctx.finding(
                                    RULE, loc,
                                    f"{short}(...) over axis {name!r} "
                                    f"which no mesh declares "
                                    f"(known axes: "
                                    f"{', '.join(sorted(declared))})",
                                    self.symbol))
                self.generic_visit(vnode)

        V().visit(ctx.tree)
        return findings


def _axis_arg(call: ast.Call, pos: int):
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    # jax.lax spells the parameter `axis_name`; a bare `axis=` kwarg on
    # all_gather/all_to_all is the tensor dimension, not the axis name.
    return call.args[pos] if len(call.args) > pos else None


def _axis_literals(ctx: FileContext, node):
    """Yield (axis_name, location_node) for statically-known axis args."""
    if node is None:
        return
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value, node
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            yield from _axis_literals(ctx, e)
    elif isinstance(node, ast.Name):
        val = ctx.module_constants.get(node.id)
        if val is not None:
            yield val, node
