"""TPU201 — x64-widening detector.

``paddle_tpu/__init__.py`` enables ``jax_enable_x64`` globally (paddle's
int64 index semantics require it), which flips JAX's *default* dtypes to
float64/int64.  Any array created without an explicit dtype therefore
lands wide, and f64 on TPU is emulated — orders of magnitude slower than
f32.  The runtime HLO audit (tests/test_x64_audit.py) catches leaks that
reach a compiled train step; this pass catches them at the source line,
over the whole tree, without compiling anything.

What fires:

* 64-bit dtype *mentions* used as call arguments — ``astype(jnp.int64)``,
  ``jnp.asarray(x, np.float64)``, ``dtype="float64"``.  float64/double/
  complex128 attribute mentions additionally fire anywhere outside a
  comparison (``x.dtype == np.float64`` is a read, not a widening).
* dtype-less float-typed creation — ``jnp.zeros(shape)``, ``jnp.ones``,
  ``jnp.full``, ``jnp.empty``, ``jnp.linspace`` with no dtype argument,
  ``jnp.arange`` with a float literal bound, and ``jnp.array``/
  ``jnp.asarray`` of a bare Python float literal (or list thereof):
  under x64 all of these produce f64.

What deliberately does NOT fire:

* integer ``jnp.arange(n)`` and friends — s64 *indices* are the point of
  enabling x64 (paddle parity); the runtime audit allows s64 inputs and
  only treats s64 **compute** (:data:`S64_COMPUTE_OPS`) as a leak, and
  the static rule mirrors that split.
* bare float literals in arithmetic (``x * 0.5``) — JAX weak typing
  keeps Python scalars from committing a dtype.

The constants below are the shared vocabulary between this pass and the
runtime audit, so the two checks cannot silently diverge.
"""
from __future__ import annotations

import ast
from typing import List, Set

from .core import FileContext, Finding, LintPass, ScopedVisitor

RULE = "TPU201"

#: HLO op mnemonics on s64 operands that the *runtime* audit treats as a
#: leak (s64 params/constants are allowed: labels land as s64 under x64).
#: tests/test_x64_audit.py imports this — single source of truth.
S64_COMPUTE_OPS = ("multiply", "add", "subtract", "divide", "convert")

#: dtype names that are always a widening when passed as a dtype argument.
WIDE_DTYPE_NAMES = frozenset({"float64", "double", "complex128", "int64",
                              "longlong"})
#: the float subset additionally fires outside call arguments.
WIDE_FLOAT_NAMES = frozenset({"float64", "double", "complex128"})

#: jax.numpy creation functions with a float default dtype (f64 under x64
#: when no dtype is given).  Value = index of the positional dtype slot.
_FLOAT_CREATORS = {"jax.numpy.zeros": 1, "jax.numpy.ones": 1,
                   "jax.numpy.empty": 1, "jax.numpy.full": 2,
                   "jax.numpy.linspace": 5}
_ARRAY_CTORS = {"jax.numpy.array": 1, "jax.numpy.asarray": 1}


def _has_dtype(call: ast.Call, pos: int) -> bool:
    if any(kw.arg == "dtype" for kw in call.keywords):
        return True
    return len(call.args) > pos


def _is_float_literal(node) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, float)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.USub,
                                                              ast.UAdd)):
        return _is_float_literal(node.operand)
    return False


def _holds_float_literal(node, depth=0) -> bool:
    if _is_float_literal(node):
        return True
    if depth < 2 and isinstance(node, (ast.List, ast.Tuple)):
        return any(_holds_float_literal(e, depth + 1) for e in node.elts)
    return False


class _Visitor(ScopedVisitor):
    def __init__(self, ctx: FileContext):
        super().__init__()
        self.ctx = ctx
        self.findings: List[Finding] = []
        # attribute nodes appearing inside comparisons are dtype *reads*
        self._compare_attrs: Set[int] = set()
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Compare):
                for sub in ast.walk(n):
                    if isinstance(sub, ast.Attribute):
                        self._compare_attrs.add(id(sub))
        self._call_args: Set[int] = set()

    def _flag(self, node, msg):
        self.findings.append(self.ctx.finding(RULE, node, msg, self.symbol))

    def _is_device_dtype(self, attr: ast.Attribute) -> bool:
        """int64 only counts against device (jax.numpy / paddle dtype
        registry) references — ``np.int64`` labels in host-side dataset
        loaders are paddle parity, not a TPU widening (the runtime audit
        allows s64 *inputs* for the same reason).  The float64 family is
        flagged regardless of base."""
        if attr.attr in WIDE_FLOAT_NAMES:
            return True
        base = self.ctx.resolve(attr.value) or ""
        return base == "jax.numpy" or base.endswith("core.dtype") \
            or base == "paddle_tpu"

    def visit_Call(self, node):
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            self._call_args.add(id(arg))
            if isinstance(arg, ast.Attribute) \
                    and arg.attr in WIDE_DTYPE_NAMES \
                    and self._is_device_dtype(arg):
                self._flag(arg, f"64-bit dtype {arg.attr!r} passed as an "
                                f"argument widens under global x64")
            elif isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, str) \
                    and arg.value in WIDE_DTYPE_NAMES:
                self._flag(arg, f"64-bit dtype string {arg.value!r} widens "
                                f"under global x64")
        q = self.ctx.resolve_call(node)
        if q in _FLOAT_CREATORS and not _has_dtype(node,
                                                   _FLOAT_CREATORS[q]):
            self._flag(node, f"{q.split('.')[-1]}(...) without dtype "
                             f"defaults to float64 under global x64")
        elif q == "jax.numpy.arange" and not _has_dtype(node, 3) \
                and any(_is_float_literal(a) for a in node.args):
            self._flag(node, "arange(...) with a float bound and no dtype "
                             "produces float64 under global x64")
        elif q in _ARRAY_CTORS and not _has_dtype(node, _ARRAY_CTORS[q]) \
                and node.args and _holds_float_literal(node.args[0]):
            self._flag(node, f"{q.split('.')[-1]}(<float literal>) without "
                             f"dtype produces float64 under global x64")
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if node.attr in WIDE_FLOAT_NAMES and id(node) not in self._call_args \
                and id(node) not in self._compare_attrs:
            self._flag(node, f"64-bit float dtype {node.attr!r} mentioned "
                             f"(f64 is emulated on TPU)")
        self.generic_visit(node)


class X64WideningPass(LintPass):
    rule = RULE
    name = "x64-widening"
    description = ("float64/int64 dtype mentions and dtype-less creation "
                   "that widen under the globally-enabled x64 mode")

    def check(self, ctx: FileContext):
        v = _Visitor(ctx)
        v.visit(ctx.tree)
        return v.findings
