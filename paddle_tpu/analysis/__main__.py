"""CLI for tpu-lint: ``python -m paddle_tpu.analysis [paths] [--strict]``.

Exit codes: 0 clean (or findings without --strict), 1 findings under
--strict, 2 operational error (unparsable file, bad baseline).
"""
from __future__ import annotations

import argparse
import os
import sys

from . import ALL_PASSES, RULES, Analyzer
from .baseline import BaselineFormatError


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="tpu-lint — static analysis for the paddle_tpu tree")
    ap.add_argument("paths", nargs="*", default=["paddle_tpu"],
                    help="files/directories to analyze (default: paddle_tpu)")
    ap.add_argument("--root", default=os.getcwd(),
                    help="repo root for relative paths + baseline "
                         "(default: cwd)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any unsuppressed finding remains")
    ap.add_argument("--baseline", default="auto",
                    help="baseline file (default: "
                         "<root>/tools/tpu_lint_baseline.txt if present); "
                         "'none' disables")
    ap.add_argument("--select", default=None, metavar="RULES",
                    help="comma-separated rule ids to run "
                         f"(available: {', '.join(sorted(RULES))})")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="findings only, no summary")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, cls in sorted(RULES.items()):
            print(f"{rule}  {cls.name:<18} {cls.description}")
        return 0

    passes = ALL_PASSES
    if args.select:
        wanted = {r.strip() for r in args.select.split(",") if r.strip()}
        unknown = wanted - set(RULES)
        if unknown:
            print(f"unknown rules: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        passes = [RULES[r] for r in sorted(wanted)]

    baseline = None if args.baseline == "none" else args.baseline
    try:
        analyzer = Analyzer(root=args.root, passes=passes,
                            baseline_path=baseline)
        report = analyzer.run(args.paths)
    except (BaselineFormatError, OSError) as e:
        print(f"tpu-lint: {e}", file=sys.stderr)
        return 2

    for f in report.findings:
        print(f.format())
    for s in report.stale_baseline:
        print(f"warning: stale baseline entry — {s}", file=sys.stderr)
    for e in report.errors:
        print(f"error: {e}", file=sys.stderr)
    if not args.quiet:
        print(f"tpu-lint: {report.summary()}", file=sys.stderr)

    if report.errors:
        return 2
    if report.findings and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
