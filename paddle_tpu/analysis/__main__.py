"""CLI for the analysis tiers.

* tpu-lint (AST):   ``python -m paddle_tpu.analysis [paths] [--strict]``
* tpu-audit (trace): ``python -m paddle_tpu.analysis --trace [programs]
  [--select TPU504] [--strict]`` — positional args become fnmatch
  patterns over canonical-program names (``'pallas/*'``).
* tpu-race (concurrency): ``python -m paddle_tpu.analysis --concurrency
  [paths] [--strict]`` — the TPU6xx call-graph tier over the declared
  thread roles (paths scope the scanned tree, default ``paddle_tpu``).
* tpu-flow (dataflow): ``python -m paddle_tpu.analysis --flow [paths]
  [--strict]`` — the TPU7xx exception-edge dataflow tier over the
  declared resource/pairing registry.

``--select`` filters rules within the chosen tier; ``--list-rules``
prints the unified catalogue (rule, pass, tier, summary) for all four.

``--format json`` emits one machine-readable JSON document on stdout;
``--format github`` emits GitHub workflow annotation lines
(``::error ...``) per finding so CI surfaces them inline on the PR.

Exit codes: 0 clean (or findings without --strict), 1 findings under
--strict, 2 operational error (unparsable file, bad baseline, broken
program builder).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from . import (ALL_PASSES, CONCURRENCY_RULES, FLOW_RULES, RULES,
               TRACE_RULES, Analyzer)
from .baseline import BaselineFormatError


def _emit(report, fmt: str, quiet: bool, skipped=()):
    if fmt == "json":
        doc = {
            "ok": report.ok,
            "files": report.files,
            "findings": [{
                "rule": f.rule, "path": f.path, "line": f.line,
                "col": f.col, "symbol": f.symbol, "message": f.message,
            } for f in report.findings],
            "baselined": len(report.baselined),
            "inline_suppressed": len(report.inline_suppressed),
            "stale_baseline": list(report.stale_baseline),
            "errors": list(report.errors),
            "skipped": list(skipped),
        }
        print(json.dumps(doc, indent=1, sort_keys=True))
        return
    if fmt == "github":
        for f in report.findings:
            # %0A is the annotation-format newline escape
            msg = f.message.replace("%", "%25").replace("\n", "%0A")
            print("::error file=%s,line=%d,title=%s [%s]::%s"
                  % (f.path, max(1, f.line), f.rule, f.symbol, msg))
        for e in report.errors:
            print("::error title=tpu-lint operational error::%s"
                  % e.replace("%", "%25").replace("\n", "%0A"))
    else:
        for f in report.findings:
            print(f.format())
    for s in report.stale_baseline:
        print(f"warning: stale baseline entry — {s}", file=sys.stderr)
    for s in skipped:
        # loud: a skipped builder means the strict gate is auditing FEWER
        # programs than CI does — usually a missing shell-level
        # XLA_FLAGS=--xla_force_host_platform_device_count=8 (it must be
        # set before `import paddle_tpu` initializes the jax backend)
        print(f"warning: SKIPPED program builder — {s}", file=sys.stderr)
    for e in report.errors:
        print(f"error: {e}", file=sys.stderr)
    if not quiet:
        print(f"tpu-lint: {report.summary()}", file=sys.stderr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="tpu-lint (AST) / tpu-audit (trace) — static analysis "
                    "for the paddle_tpu tree")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to analyze (default: "
                         "paddle_tpu); with --trace: fnmatch patterns "
                         "over canonical program names (default: all)")
    ap.add_argument("--root", default=os.getcwd(),
                    help="repo root for relative paths + baseline "
                         "(default: cwd)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any unsuppressed finding remains")
    ap.add_argument("--trace", action="store_true",
                    help="run the trace tier (TPU5xx) over the canonical "
                         "program registry instead of the AST tier")
    ap.add_argument("--concurrency", action="store_true",
                    help="run the concurrency tier (TPU6xx): package-wide "
                         "call-graph audit from the declared thread roles")
    ap.add_argument("--flow", action="store_true",
                    help="run the flow tier (TPU7xx): per-function "
                         "exception-edge dataflow over the declared "
                         "resource/pairing registry")
    ap.add_argument("--baseline", default="auto",
                    help="baseline file (default: "
                         "<root>/tools/tpu_lint_baseline.txt if present); "
                         "'none' disables")
    ap.add_argument("--select", default=None, metavar="RULES",
                    help="comma-separated rule ids to run (AST: %s; "
                         "trace: %s; concurrency: %s; flow: %s)"
                         % (", ".join(sorted(RULES)),
                            ", ".join(sorted(TRACE_RULES)),
                            ", ".join(sorted(CONCURRENCY_RULES)),
                            ", ".join(sorted(FLOW_RULES))))
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--format", default="text",
                    choices=("text", "json", "github"),
                    help="finding output format (default: text; 'github' "
                         "emits ::error workflow annotations)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="findings only, no summary")
    args = ap.parse_args(argv)

    if args.list_rules:
        # one table across all four tiers: rule, pass, tier, summary
        for tier, cat in (("ast", RULES), ("trace", TRACE_RULES),
                          ("concurrency", CONCURRENCY_RULES),
                          ("flow", FLOW_RULES)):
            for rule, cls in sorted(cat.items()):
                print(f"{rule}  {cls.name:<18} {tier:<12} "
                      f"{cls.description}")
        return 0

    if sum((args.trace, args.concurrency, args.flow)) > 1:
        print("--trace, --concurrency and --flow are separate tiers; "
              "run them as separate invocations", file=sys.stderr)
        return 2

    catalogue = (TRACE_RULES if args.trace
                 else CONCURRENCY_RULES if args.concurrency
                 else FLOW_RULES if args.flow else RULES)
    passes = None
    if args.select:
        wanted = {r.strip() for r in args.select.split(",") if r.strip()}
        unknown = wanted - set(catalogue)
        if unknown:
            print(f"unknown rules: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        passes = [catalogue[r] for r in sorted(wanted)]

    baseline = None if args.baseline == "none" else args.baseline
    skipped = ()
    try:
        if args.trace:
            from .trace import TraceAnalyzer, build_programs
            programs, skipped, errors = build_programs(args.paths or None)
            analyzer = TraceAnalyzer(root=args.root, passes=passes,
                                     baseline_path=baseline)
            report = analyzer.run(programs, errors=errors,
                                  partial=bool(args.paths))
            if not programs and not errors:
                report.errors.append(
                    "trace registry built 0 programs (patterns %r) — an "
                    "empty audit must not pass" % (args.paths,))
        elif args.concurrency:
            from .concurrency import ConcurrencyAnalyzer
            analyzer = ConcurrencyAnalyzer(root=args.root, passes=passes,
                                           baseline_path=baseline)
            report = analyzer.run(args.paths or None)
        elif args.flow:
            from .flow import FlowAnalyzer
            analyzer = FlowAnalyzer(root=args.root, passes=passes,
                                    baseline_path=baseline)
            report = analyzer.run(args.paths or None)
        else:
            analyzer = Analyzer(root=args.root, passes=passes,
                                baseline_path=baseline)
            report = analyzer.run(args.paths or ["paddle_tpu"])
    except (BaselineFormatError, OSError) as e:
        print(f"tpu-lint: {e}", file=sys.stderr)
        return 2

    _emit(report, args.format, args.quiet, skipped)

    if report.errors:
        return 2
    if report.findings and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
