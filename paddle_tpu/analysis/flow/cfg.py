"""Per-function control-flow graph with explicit exception edges.

The flow tier's passes are *path-sensitive* about one thing the AST and
call-graph tiers cannot see: what happens on the paths an exception
takes out of a function.  This module builds, per ``def``, a CFG whose
nodes are **statements** (compound statements contribute their *header*
— the ``if``/``while`` test, the ``for`` iterable, the ``with`` items —
as one node and their bodies as further nodes) and whose edges come in
two kinds:

* ``succ`` — normal control transfer.  Dataflow along these edges uses
  the statement's **post**-state (gen/kill applied).
* ``exc`` — an exception raised *during* the statement.  Any statement
  that contains a call (or is a ``raise``/``assert``) gets an ``exc``
  edge to the innermost enclosing handler entry, through any enclosing
  ``finally`` body, or — when nothing encloses it — to :data:`EXIT`.
  Dataflow along these edges uses the statement's **pre**-state: an
  acquisition that raises never bound its result.

Deliberate, documented approximations (see ANALYSIS.md §Tier 4):

* A ``try`` handler whose type is not a catch-all still receives an
  edge from every raising statement in the body **and** the exception
  is also propagated outward (may-analysis: both continuations exist).
* ``finally`` bodies are modeled on the fall-through and exception
  paths; an early ``return`` inside ``try``/``finally`` goes straight
  to :data:`EXIT` without re-executing the modeled ``finally``.
* ``with`` is control-flow only: ``__exit__`` cleanup semantics are not
  modeled (the serving tree's page resources are not context managers).
* Nested ``def``/``lambda``/``class`` bodies are opaque single nodes.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

__all__ = ["CFG", "EXIT", "build_cfg", "stmt_may_raise"]

#: synthetic exit node id: normal ``succ`` edges into EXIT are returns /
#: fall-off-the-end; ``exc`` edges into EXIT are uncaught exceptions.
EXIT = -1

_CATCH_ALL = {"Exception", "BaseException"}


def _expr_may_raise(*exprs) -> bool:
    """True when evaluating any of the expressions can raise: contains a
    call (skipping lambda bodies, whose calls do not run at def site)."""
    for e in exprs:
        if e is None:
            continue
        stack = [e]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Call):
                return True
            if isinstance(n, ast.Lambda):
                continue
            stack.extend(ast.iter_child_nodes(n))
    return False


def stmt_may_raise(stmt: ast.stmt) -> bool:
    """May executing this statement's *header* raise?  Compound bodies
    are separate nodes and judged on their own."""
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    if isinstance(stmt, ast.If) or isinstance(stmt, ast.While):
        return _expr_may_raise(stmt.test)
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return _expr_may_raise(stmt.iter)
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return _expr_may_raise(*[i.context_expr for i in stmt.items])
    if isinstance(stmt, ast.Return):
        return _expr_may_raise(stmt.value)
    if isinstance(stmt, ast.Try):
        return False
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return False                    # decorators at def-time: ignored
    if isinstance(stmt, ast.Match):
        return _expr_may_raise(stmt.subject)
    return _expr_may_raise(stmt)


def _is_catch_all(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        if isinstance(n, ast.Name) and n.id in _CATCH_ALL:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _CATCH_ALL:
            return True
    return False


class _Target:
    """Where exceptions raised under some region go.  Sources (raising
    node ids) and sinks (handler-entry ids / outer targets / EXIT) both
    arrive incrementally; the cross product is wired as they do."""

    def __init__(self, builder: "_Builder"):
        self.b = builder
        self.sources: List[int] = []
        self._entries: List[int] = []
        self._targets: List["_Target"] = []

    def add_source(self, nid: int) -> None:
        self.sources.append(nid)
        for e in self._entries:
            self.b.exc[nid].add(e)
        for t in self._targets:
            t.add_source(nid)

    def add_entry(self, nid: int) -> None:
        self._entries.append(nid)
        for s in self.sources:
            self.b.exc[s].add(nid)

    def add_target(self, t: "_Target") -> None:
        self._targets.append(t)
        for s in self.sources:
            t.add_source(s)


def _match_none_test(test: ast.AST):
    """``if X is None`` / ``if not X`` → ('X', True): X is None/empty on
    the true branch; ``if X is not None`` / ``if X`` → ('X', False)."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.left, ast.Name) \
            and len(test.comparators) == 1 \
            and isinstance(test.comparators[0], ast.Constant) \
            and test.comparators[0].value is None:
        if isinstance(test.ops[0], ast.Is):
            return test.left.id, True
        if isinstance(test.ops[0], ast.IsNot):
            return test.left.id, False
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and isinstance(test.operand, ast.Name):
        return test.operand.id, True
    if isinstance(test, ast.Name):
        return test.id, False
    return None


class CFG:
    """nodes[i] is the statement for node id ``i``; ``succ``/``exc`` map
    node id → successor ids (:data:`EXIT` included).  ``edge_null``
    marks normal edges on which a name is statically known to be
    None/empty (``if x is None: ...``) — path-sensitive facts the
    lifetime dataflow subtracts per-edge."""

    def __init__(self, nodes: List[ast.stmt], succ: Dict[int, Set[int]],
                 exc: Dict[int, Set[int]], entry: int,
                 edge_null: Dict[tuple, str]):
        self.nodes = nodes
        self.succ = succ
        self.exc = exc
        self.entry = entry
        self.edge_null = edge_null

    def preds(self):
        """(normal_preds, exc_preds): node id → set of predecessor ids."""
        np: Dict[int, Set[int]] = {}
        ep: Dict[int, Set[int]] = {}
        for src, dsts in self.succ.items():
            for d in dsts:
                np.setdefault(d, set()).add(src)
        for src, dsts in self.exc.items():
            for d in dsts:
                ep.setdefault(d, set()).add(src)
        return np, ep


class _Builder:
    def __init__(self):
        self.nodes: List[ast.stmt] = []
        self.succ: Dict[int, Set[int]] = {}
        self.exc: Dict[int, Set[int]] = {}
        self.edge_null: Dict[tuple, str] = {}
        # fallthrough null facts resolved after all edges are wired:
        # (header id, name, exempt body-entry id or None)
        self.pending_null: List[tuple] = []
        # each loop frame: (header id, [break node ids])
        self.loops: List[list] = []

    def new(self, stmt: ast.stmt) -> int:
        nid = len(self.nodes)
        self.nodes.append(stmt)
        self.succ[nid] = set()
        self.exc[nid] = set()
        return nid

    def wire(self, frontier: Set[int], nid: int) -> None:
        for f in frontier:
            self.succ[f].add(nid)

    # -- statement dispatch --------------------------------------------------
    def block(self, stmts, frontier: Set[int], target: _Target) -> Set[int]:
        for s in stmts:
            frontier = self.stmt(s, frontier, target)
        return frontier

    def stmt(self, s: ast.stmt, frontier: Set[int],
             target: _Target) -> Set[int]:
        if isinstance(s, ast.Try):
            return self._try(s, frontier, target)
        if isinstance(s, ast.If):
            return self._if(s, frontier, target)
        if isinstance(s, (ast.While,)):
            return self._while(s, frontier, target)
        if isinstance(s, (ast.For, ast.AsyncFor)):
            return self._for(s, frontier, target)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            return self._with(s, frontier, target)
        if isinstance(s, ast.Match):
            return self._match(s, frontier, target)

        nid = self.new(s)
        self.wire(frontier, nid)
        if stmt_may_raise(s):
            target.add_source(nid)
        if isinstance(s, ast.Return):
            self.succ[nid].add(EXIT)
            return set()
        if isinstance(s, ast.Raise):
            return set()                # exc edge is the only way out
        if isinstance(s, ast.Break):
            if self.loops:
                self.loops[-1][1].append(nid)
            return set()
        if isinstance(s, ast.Continue):
            if self.loops:
                self.succ[nid].add(self.loops[-1][0])
            return set()
        return {nid}

    # -- compound statements -------------------------------------------------
    def _if(self, s, frontier, target):
        nid = self.new(s)
        self.wire(frontier, nid)
        if stmt_may_raise(s):
            target.add_source(nid)
        nt = _match_none_test(s.test)
        body_first = len(self.nodes)
        then = self.block(s.body, {nid}, target)
        body_entry = body_first if len(self.nodes) > body_first else None
        if nt is not None:
            name, on_true = nt
            if on_true and body_entry is not None:
                self.edge_null[(nid, body_entry)] = name
            elif not on_true:
                if s.orelse:
                    orelse_first = len(self.nodes)
                    els = self.block(s.orelse, {nid}, target)
                    if len(self.nodes) > orelse_first:
                        self.edge_null[(nid, orelse_first)] = name
                    return then | els
                self.pending_null.append((nid, name, body_entry))
        if s.orelse:
            els = self.block(s.orelse, {nid}, target)
        else:
            els = {nid}
        return then | els

    def _while(self, s, frontier, target):
        nid = self.new(s)
        self.wire(frontier, nid)
        if stmt_may_raise(s):
            target.add_source(nid)
        self.loops.append([nid, []])
        body = self.block(s.body, {nid}, target)
        self.wire(body, nid)            # back edge
        _, breaks = self.loops.pop()
        infinite = (isinstance(s.test, ast.Constant)
                    and bool(s.test.value) is True)
        out = set(breaks) if infinite else {nid} | set(breaks)
        if s.orelse:
            out = self.block(s.orelse, out, target) | set(breaks)
        return out

    def _for(self, s, frontier, target):
        nid = self.new(s)
        self.wire(frontier, nid)
        if stmt_may_raise(s):
            target.add_source(nid)
        self.loops.append([nid, []])
        body = self.block(s.body, {nid}, target)
        self.wire(body, nid)            # back edge
        _, breaks = self.loops.pop()
        out = {nid} | set(breaks)
        if s.orelse:
            out = self.block(s.orelse, out, target) | set(breaks)
        return out

    def _with(self, s, frontier, target):
        nid = self.new(s)
        self.wire(frontier, nid)
        if stmt_may_raise(s):
            target.add_source(nid)
        return self.block(s.body, {nid}, target)

    def _match(self, s, frontier, target):
        nid = self.new(s)
        self.wire(frontier, nid)
        if stmt_may_raise(s):
            target.add_source(nid)
        out: Set[int] = {nid}           # no case may match
        for case in s.cases:
            out |= self.block(case.body, {nid}, target)
        return out

    def _try(self, s, frontier, target):
        catch_all = any(_is_catch_all(h) for h in s.handlers)
        body_t = _Target(self)
        # exceptions escaping the handlers / orelse / propagating past a
        # non-catch-all handler set route through the finally body (when
        # present) and then outward.
        after_t = _Target(self)
        body_out = self.block(s.body, frontier, body_t)
        if s.orelse:
            body_out = self.block(s.orelse, body_out, after_t)

        handler_outs: Set[int] = set()
        for h in s.handlers:
            entry_frontier: Set[int] = set()
            first_len = len(self.nodes)
            h_out = self.block(h.body, entry_frontier, after_t)
            if len(self.nodes) > first_len:
                body_t.add_entry(first_len)
            handler_outs |= h_out
        if not s.handlers or not catch_all:
            body_t.add_target(after_t)

        out = body_out | handler_outs
        if s.finalbody:
            fin_t = _Target(self)       # raises inside finally: outward
            fin_t.add_target(target)
            first_len = len(self.nodes)
            fin_out = self.block(s.finalbody, out, fin_t)
            if len(self.nodes) > first_len:
                after_t.add_entry(first_len)
                # pending-exception continuation: finally exit → outer
                for f in fin_out:
                    target.add_source(f)
            else:
                after_t.add_target(target)
            return fin_out
        after_t.add_target(target)
        return out


def build_cfg(fn: ast.AST) -> CFG:
    """Build the CFG for one ``FunctionDef`` / ``AsyncFunctionDef``."""
    b = _Builder()
    top = _Target(b)
    frontier = b.block(fn.body, set(), top)
    for f in frontier:
        b.succ[f].add(EXIT)             # fall off the end
    top.add_entry(EXIT)
    for nid, name, body_entry in b.pending_null:
        for t in b.succ[nid]:
            if t != body_entry:
                b.edge_null[(nid, t)] = name
    entry = 0 if b.nodes else EXIT
    return CFG(b.nodes, b.succ, b.exc, entry, b.edge_null)
