"""tpu-flow — tier 4 of the static analysis stack: the exception-edge
dataflow audit (rules TPU7xx).

Where tier 1 (tpu-lint) checks each file's AST, tier 2 (tpu-audit) the
traced programs, and tier 3 (tpu-race) the thread structure, this tier
checks the *paths* through each serving function: a per-function CFG
with explicit exception edges (:mod:`.cfg`) driven by a declarative
resource/pairing registry (:mod:`.resources`), with three passes
(:mod:`.rules`):

=======  ===============================================================
TPU701   page handle acquired but not released / transferred on every
         path out of the function — **including raise edges** (the
         leak-on-exception class PRs 7/12/14/16 each caught by hand)
TPU702   watched jit entry called with an unbounded python scalar, or
         a jitted closure over post-construction-rebound ``self``
         state — the static complement of the recompile watchdog
TPU703   host-side mirror write (``cache_len``/``_len_host``/page
         table) without its paired device op in scope or a declared
         delegation
=======  ===============================================================

Run it with ``python -m paddle_tpu.analysis --flow --strict``.
Suppressions are the AST tier's, unchanged: inline
``# tpu-lint: disable=TPU70x`` or a reasoned entry in
``tools/tpu_lint_baseline.txt`` (TPU7xx entries are scoped to this
tier — no other tier stale-flags them).  See ANALYSIS.md §Tier 4.
"""
from .cfg import CFG, EXIT, build_cfg, stmt_may_raise
from .core import FlowAnalyzer
from .resources import DEFAULT_REGISTRY as DEFAULT_FLOW_REGISTRY
from .resources import MirrorSpec, ResourceRegistry
from .rules import (FlowContext, FlowPass, MirrorCoherencePass,
                    PageLifetimePass, RetraceHazardPass)

FLOW_PASSES = [PageLifetimePass, RetraceHazardPass, MirrorCoherencePass]
FLOW_RULES = {p.rule: p for p in FLOW_PASSES}

__all__ = [
    "CFG", "DEFAULT_FLOW_REGISTRY", "EXIT", "FLOW_PASSES", "FLOW_RULES",
    "FlowAnalyzer", "FlowContext", "FlowPass", "MirrorCoherencePass",
    "MirrorSpec", "PageLifetimePass", "ResourceRegistry",
    "RetraceHazardPass", "build_cfg", "stmt_may_raise",
]
