"""The flow tier's declarative resource / pairing registry.

Same contract as the concurrency tier's ``roles.py``: every entry is a
**declaration with a mandatory reason string** — the reason is the
review artifact, and an empty registry is an exit-2 error, never a
silent green.  Three rule families consume it:

* **TPU701** (page-lifetime balance) reads ``modules`` /``acquires`` /
  ``releases`` / ``transfers``: within the declared serving modules,
  every value returned by an *acquire* call must, on every CFG path
  leaving the function — including exception edges — reach a *release*
  call, a *transfer* into a tracked owner structure (assignment into an
  attribute/subscript, a declared transfer call, or being returned),
  or a compensating handler that does the same.

  The acquire/release/transfer sets are **caller-side** vocabulary:
  ``adopt_page`` appears under *transfers* because the caller hands the
  page over to the allocator's cached pool (from the allocator's own
  point of view it is an acquisition — that side is its internal
  bookkeeping, checked by its own function's dataflow).

* **TPU702** (retrace hazard) reads ``jit_entries`` / ``jit_closures``
  / ``bounded_sources`` / ``array_wrappers`` / ``ctor_methods``: the
  statically-declared complement of the runtime recompile watchdog.

* **TPU703** (mirror coherence) reads ``mirrors``: pairs of host-side
  mirror writes and the device-side ops they must co-occur with, plus
  the explicitly-delegated reconciliation functions.

Registry drift (a declared class/function that no longer resolves in a
scanned module) is an exit-2 error: rename the code and the registry in
the same PR.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Tuple

__all__ = ["MirrorSpec", "ResourceRegistry", "DEFAULT_REGISTRY"]

_SCHED = "paddle_tpu.serving.scheduler"
_ENGINE = "paddle_tpu.serving.engine"
_PAGES = "paddle_tpu.serving.pages"
_DISAGG = "paddle_tpu.serving.disagg"
_KVTIER = "paddle_tpu.serving.kv_tier"


@dataclass(frozen=True)
class MirrorSpec:
    """One host↔device mirror pair for TPU703.

    A function in one of ``modules`` that writes any ``host_attrs``
    attribute (plain store, augmented store, or element store through
    it) must, in the same body, either call one of ``device_calls`` or
    write one of ``device_attrs`` — unless it is listed in
    ``ctor_methods`` (initialisation, not mutation) or ``delegates``
    (the device-side op happened elsewhere, reason required).
    """
    name: str
    modules: Dict[str, str]
    host_attrs: Tuple[str, ...]
    device_calls: Dict[str, str]
    device_attrs: Dict[str, str] = field(default_factory=dict)
    ctor_methods: Dict[str, str] = field(default_factory=dict)
    delegates: Dict[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class ResourceRegistry:
    # -- TPU701 --------------------------------------------------------------
    #: module → why its functions are subject to page-lifetime dataflow
    modules: Dict[str, str] = field(default_factory=dict)
    #: call name → why its return value is an owned page / page list
    acquires: Dict[str, str] = field(default_factory=dict)
    #: call name → why passing a handle to it ends the obligation
    releases: Dict[str, str] = field(default_factory=dict)
    #: call name → which tracked owner structure the handle moves into
    transfers: Dict[str, str] = field(default_factory=dict)
    # -- TPU702 --------------------------------------------------------------
    #: "module:Class.attr" of a watchdog-watched jitted entry → reason
    jit_entries: Dict[str, str] = field(default_factory=dict)
    #: "module:Class.method.closure" of a jitted closure body → reason
    jit_closures: Dict[str, str] = field(default_factory=dict)
    #: call name whose result is bounded (bucketing/clamping) → reason
    bounded_sources: Dict[str, str] = field(default_factory=dict)
    #: call name that produces an array (traced, not a cache key) → reason
    array_wrappers: Dict[str, str] = field(default_factory=dict)
    #: method name treated as construction (writes there are init) → reason
    ctor_methods: Dict[str, str] = field(default_factory=dict)
    # -- TPU703 --------------------------------------------------------------
    mirrors: Tuple[MirrorSpec, ...] = ()

    def empty(self) -> bool:
        return not (self.modules or self.acquires or self.jit_entries
                    or self.mirrors)


#: the production registry for the serving stack.
DEFAULT_REGISTRY = ResourceRegistry(
    modules={
        _SCHED: "owns admission/preempt/fetch state machines that "
                "allocate pages on behalf of the engine",
        _ENGINE: "owns the paged KV cache and every COW/import path",
        _PAGES: "the allocator itself: internal free-list moves must "
                "balance too",
        _DISAGG: "prefill→decode handoff allocates on the decode side "
                 "across a network boundary",
        _KVTIER: "host tier stages page payloads against a byte budget",
    },
    acquires={
        "alloc": "PageAllocator.alloc pops a free page the caller now "
                 "owns until mapped/adopted/released",
        "_fetch_alloc": "scheduler helper: returns a list of owned "
                        "pages for a host-tier fetch (or None)",
    },
    releases={
        "_release": "refcount decrement returns the page to the free "
                    "list at zero",
        "free_slot": "releases every page mapped in the slot row",
        "evict_cached": "drops a cached (refcount-0) page to the free "
                        "list",
    },
    transfers={
        "map": "page becomes owned by the slot table row",
        "share": "prefix page mapped with a refcount bump — table-owned",
        "remap": "COW replacement: new page enters the table, old ref "
                 "dropped inside",
        "adopt_page": "page moves into the allocator's cached pool "
                      "(hash-indexed, evictable)",
    },
    jit_entries={
        f"{_ENGINE}:DecodeEngine._decode":
            "watch('serving.decode') — the per-token hot path",
        f"{_ENGINE}:DecodeEngine._verify":
            "watch('serving.spec_verify') — speculative verify batch",
        f"{_ENGINE}:DecodeEngine._prefill":
            "watch('serving.prefill', expected=len(buckets)) — slotted "
            "prefill, bucketed",
        f"{_ENGINE}:DecodeEngine._prefill_chunk":
            "watch('serving.prefill_chunk') — paged chunked prefill",
        f"{_ENGINE}:DecodeEngine._cow":
            "watch('serving.cow_copy') — copy-on-write page clone",
        f"{_ENGINE}:DecodeEngine._kv_export":
            "watch('serving.kv_export') — page payload gather",
        f"{_ENGINE}:DecodeEngine._kv_import":
            "watch('serving.kv_import') — page payload scatter",
    },
    jit_closures={
        f"{_ENGINE}:DecodeEngine._init_paged.decode_fn":
            "body of serving.decode: must close only over "
            "shape-constant config, never rebindable state",
        f"{_ENGINE}:DecodeEngine._init_paged.verify_fn":
            "body of serving.spec_verify",
        f"{_ENGINE}:DecodeEngine._init_paged.prefill_chunk_fn":
            "body of serving.prefill_chunk",
        f"{_ENGINE}:DecodeEngine._init_paged.cow_copy_fn":
            "body of serving.cow_copy",
        f"{_ENGINE}:DecodeEngine._init_paged.kv_export_fn":
            "body of serving.kv_export",
        f"{_ENGINE}:DecodeEngine._init_paged.kv_import_fn":
            "body of serving.kv_import",
        f"{_ENGINE}:DecodeEngine._init_slotted.decode_fn":
            "body of the slotted serving.decode",
        f"{_ENGINE}:DecodeEngine._init_slotted.prefill_fn":
            "body of the slotted serving.prefill",
    },
    bounded_sources={
        "bucket_for": "pads a length up to the declared bucket ladder — "
                      "finitely many traced shapes",
        "min": "clamped above by the other operand",
    },
    array_wrappers={
        "int32": "np/jnp scalar array: traced operand, not a python "
                 "cache key",
        "asarray": "array operand",
        "array": "array operand",
        "zeros": "array operand",
        "full": "array operand",
    },
    ctor_methods={
        "__init__": "construction",
        "__new__": "construction",
        "_init_paged": "called from __init__ only: builds the paged "
                       "cache + jit entries",
        "_init_slotted": "called from __init__ only: slotted layout",
        "reset": "whole-engine reinitialisation to the "
                 "post-construction state (serving loop is stopped)",
    },
    mirrors=(
        MirrorSpec(
            name="slot-length",
            modules={
                _SCHED: "act.cache_len mirrors device lengths per slot",
                _ENGINE: "_len_host mirrors the device lengths array",
                _DISAGG: "handoff finish must set both sides",
            },
            host_attrs=("cache_len", "_len_host"),
            device_calls={
                "_set_length": "writes _len_host AND rebuilds the "
                               "device lengths in one place",
                "PagedKVCache": "rebuilding the cache pytree IS the "
                                "device-side lengths write",
                "_decode": "decode program advances device lengths "
                           "in-dispatch",
                "_verify": "verify program advances device lengths "
                           "in-dispatch",
                "_prefill": "slotted prefill writes device lengths",
                "_prefill_chunk": "chunk program writes device lengths",
                "prefill": "engine.prefill sets device length for the "
                           "admitted slot",
                "prefill_step": "paged chunked prefill advances device "
                                "length",
                "_run_prefill_chunk": "scheduler wrapper that dispatches "
                                      "engine.prefill_step",
                "free_slot": "slot teardown zeroes both sides",
            },
            ctor_methods={
                "__init__": "construction",
                "_init_paged": "construction helper",
                "_init_slotted": "construction helper",
                "reset": "reinitialisation with the loop stopped",
            },
            delegates={
                f"{_SCHED}:ContinuousBatchingScheduler._consume_inflight":
                    "mirrors the finalize of an ALREADY-dispatched "
                    "decode/verify program at its one allowlisted "
                    "fetch point (TPU602) — the device advance "
                    "happened at submit",
                f"{_ENGINE}:DecodeEngine.decode_spec_fetch":
                    "reconciles _len_host with the verify program's "
                    "per-slot accept counts after the fetch — device "
                    "side advanced at decode_spec_submit",
            },
        ),
        MirrorSpec(
            name="device-page-table",
            modules={
                _PAGES: "table mutations must invalidate the memoised "
                        "device copy or stale mappings reach the kernel",
            },
            host_attrs=("table",),
            device_calls={},
            device_attrs={
                "_device_table": "None-ing the memo forces re-upload on "
                                 "next device_table()",
            },
            ctor_methods={
                "__init__": "construction",
                "reset": "rebuilds table and memo together",
            },
        ),
    ),
)
