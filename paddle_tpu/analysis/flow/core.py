"""The flow-tier analyzer: contexts → call graph → registry
resolution → CFG dataflow passes → :class:`~paddle_tpu.analysis.core.Report`.

Operational discipline matches the concurrency tier exactly:

* an empty resource registry is an **error** (exit 2) — a lifetime
  audit with no declared resources checks nothing;
* a registry entry whose module IS scanned but whose class/def/closure
  no longer exists is **drift** (error): move the registry line in the
  same PR that moved the code;
* entries for unscanned modules are skipped silently so targeted runs
  stay useful — but if the registry matches *nothing at all* in the
  scanned paths, that is again an error, never a silent green;
* baseline entries are shared with ``tools/tpu_lint_baseline.txt`` and
  scoped per-tier: this analyzer loads only TPU7xx entries.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..baseline import Baseline
from ..core import FileContext, Finding, Report, _iter_py_files, \
    fold_findings
from ..concurrency.graph import CallGraph
from .resources import DEFAULT_REGISTRY, ResourceRegistry
from .rules import FlowContext

__all__ = ["FlowAnalyzer"]


def _drift(errors: List[str], label: str, spec: str, what: str):
    errors.append(
        f"flow registry drift: {label} entry '{spec}' {what} in the "
        f"scanned tree — update analysis/flow/resources.py in the same "
        f"change that moved it")


class FlowAnalyzer:
    """Run the TPU7xx passes over a file tree."""

    def __init__(self, root: Optional[str] = None, passes=None,
                 baseline_path: Optional[str] = "auto",
                 registry: Optional[ResourceRegistry] = None):
        from . import FLOW_PASSES
        self.root = os.path.abspath(root or os.getcwd())
        self.passes = [p() if isinstance(p, type) else p
                       for p in (passes if passes is not None
                                 else FLOW_PASSES)]
        self.registry = registry if registry is not None else \
            DEFAULT_REGISTRY
        if baseline_path == "auto":
            baseline_path = os.path.join(self.root, "tools",
                                         "tpu_lint_baseline.txt")
            if not os.path.exists(baseline_path):
                baseline_path = None
        base = Baseline.load(baseline_path) if baseline_path \
            else Baseline([])
        # only this tier's entries — the other tiers' runs own the rest
        self.baseline = base.subset(lambda e: e.rule.startswith("TPU7"))

    # -- registry resolution -------------------------------------------------
    def _resolve_entries(self, graph: CallGraph, errors: List[str]):
        """jit_entries → (module, class) → watched attr set, with drift
        checks (class must exist and assign the attr somewhere)."""
        out: Dict[Tuple[str, str], Set[str]] = {}
        for spec in self.registry.jit_entries:
            mod, rest = spec.split(":", 1)
            if mod not in graph.modules:
                continue
            cls, attr = rest.rsplit(".", 1)
            members = [i for i in graph.fns.values()
                       if i.module == mod and i.cls == cls]
            if not members:
                _drift(errors, "jit_entries", spec,
                       f"names class '{cls}' which no longer exists")
                continue
            assigned = any(
                isinstance(n, ast.Assign)
                and any(isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self" and t.attr == attr
                        for t in n.targets)
                for i in members for n in ast.walk(i.node))
            if not assigned:
                _drift(errors, "jit_entries", spec,
                       f"names attribute '{attr}' that no method of "
                       f"'{cls}' assigns")
                continue
            out.setdefault((mod, cls), set()).add(attr)
        return out

    def _resolve_closures(self, graph: CallGraph, errors: List[str]):
        out = []
        for spec in self.registry.jit_closures:
            mod, rest = spec.split(":", 1)
            if mod not in graph.modules:
                continue
            owner_q, clo_name = rest.rsplit(".", 1)
            owner = graph.fns.get(f"{mod}:{owner_q}")
            if owner is None:
                _drift(errors, "jit_closures", spec,
                       f"names '{owner_q}' which matches no definition")
                continue
            clo = next(
                (n for n in ast.walk(owner.node)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))
                 and n is not owner.node and n.name == clo_name),
                None)
            if clo is None:
                _drift(errors, "jit_closures", spec,
                       f"names closure '{clo_name}' not defined inside "
                       f"'{owner_q}'")
                continue
            out.append((owner, clo))
        return out

    def _check_delegates(self, graph: CallGraph, errors: List[str]):
        for spec_obj in self.registry.mirrors:
            for spec in spec_obj.delegates:
                mod = spec.split(":", 1)[0]
                if mod not in graph.modules:
                    continue
                if graph.fns.get(spec) is None:
                    _drift(errors,
                           f"mirror '{spec_obj.name}' delegates", spec,
                           "matches no definition")

    # -- run -----------------------------------------------------------------
    def run(self, paths: Optional[Sequence[str]] = None) -> Report:
        paths = list(paths) if paths else ["paddle_tpu"]
        report = Report([], [], [], [], [])
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(self.root, p)
            if not os.path.exists(ap):
                report.errors.append(f"{p}: path does not exist")
        if self.registry.empty():
            report.errors.append(
                "flow resource registry is empty — a lifetime audit "
                "with no declared resources checks nothing; refusing a "
                "silent green")
            return report

        contexts: List[FileContext] = []
        for path in _iter_py_files(paths, self.root):
            try:
                contexts.append(FileContext(path, self.root))
            except (SyntaxError, UnicodeDecodeError) as e:
                report.errors.append(f"{path}: {e}")
        report.files = len(contexts)

        graph = CallGraph(contexts)
        lifetime_fns = [i for i in graph.fns.values()
                        if i.module in self.registry.modules]
        entry_attrs = self._resolve_entries(graph, report.errors)
        closures = self._resolve_closures(graph, report.errors)
        self._check_delegates(graph, report.errors)
        mirror_fns = any(
            i.module in spec.modules
            for spec in self.registry.mirrors
            for i in graph.fns.values())
        if contexts and not (lifetime_fns or entry_attrs or closures
                             or mirror_fns):
            report.errors.append(
                "flow registry matched zero analyzable functions in "
                "the scanned paths — scan the package root or fix the "
                "registry; refusing a silent green")

        fc = FlowContext(graph=graph, registry=self.registry,
                         lifetime_fns=lifetime_fns,
                         entry_attrs=entry_attrs, closures=closures)

        raw: List[Finding] = []
        seen = set()
        for pz in self.passes:
            for f in pz.check(fc):
                if f not in seen:       # Finding is frozen/hashable
                    seen.add(f)
                    raw.append(f)
        raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        fold_findings(report, raw, contexts, self.baseline)
        return report
