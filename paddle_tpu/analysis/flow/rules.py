"""The TPU7xx flow passes: page lifetime, retrace hazard, mirror
coherence.

All three are **intraprocedural** over the per-function exception-edge
CFG (:mod:`.cfg`) plus the concurrency tier's call graph for scoping
and class-write tables, and all three are driven exclusively by the
declared vocabulary in :mod:`.resources` — no heuristics about names
not in the registry.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..core import Finding
from ..concurrency.graph import CallGraph, FnInfo
from .cfg import EXIT, build_cfg
from .resources import MirrorSpec, ResourceRegistry

__all__ = ["FlowContext", "FlowPass", "PageLifetimePass",
           "RetraceHazardPass", "MirrorCoherencePass"]


@dataclass
class FlowContext:
    """Everything the passes need, resolved once by the analyzer."""
    graph: CallGraph
    registry: ResourceRegistry
    #: functions in the TPU701-scoped modules
    lifetime_fns: List[FnInfo] = field(default_factory=list)
    #: (module, class) → set of watched jit-entry attribute names
    entry_attrs: Dict[Tuple[str, str], Set[str]] = field(
        default_factory=dict)
    #: resolved jitted closures: (owning FnInfo, closure def node)
    closures: List[Tuple[FnInfo, ast.FunctionDef]] = field(
        default_factory=list)


class FlowPass:
    rule = "TPU700"
    name = "base"
    description = ""

    def check(self, fc: FlowContext) -> Iterable[Finding]:
        raise NotImplementedError


def _call_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _walk_shallow(node: ast.AST):
    """Walk a subtree, not descending into nested def/class/lambda."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _names_in(expr: ast.AST) -> Set[str]:
    nodes = [expr, *_walk_shallow(expr)]
    return {n.id for n in nodes if isinstance(n, ast.Name)}


def _target_names(t: ast.AST) -> Set[str]:
    """Plain Name targets of an assignment target (tuples unpacked)."""
    if isinstance(t, ast.Name):
        return {t.id}
    if isinstance(t, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in t.elts:
            out |= _target_names(e)
        return out
    return set()


# ---------------------------------------------------------------------------
# TPU701 — page-lifetime balance
# ---------------------------------------------------------------------------

class _StmtFacts:
    """gen/kill + immediately-dropped acquisitions for one CFG node."""

    __slots__ = ("gen", "kill", "dropped")

    def __init__(self):
        self.gen: Set[str] = set()
        self.kill: Set[str] = set()
        self.dropped: List[Tuple[ast.Call, str]] = []


def _header_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions a compound statement's CFG node evaluates (its
    bodies are separate nodes and must not be double-counted here)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.Match):
        return [stmt.subject]
    if isinstance(stmt, ast.Try):
        return []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return [stmt]


def _stmt_facts(stmt: ast.stmt, reg: ResourceRegistry) -> _StmtFacts:
    facts = _StmtFacts()
    consuming = set(reg.releases) | set(reg.transfers)
    roots = _header_exprs(stmt)

    # parent map over the node's own expressions
    parents: Dict[ast.AST, ast.AST] = {}
    for root in roots:
        stack = [root]
        while stack:
            n = stack.pop()
            for c in ast.iter_child_nodes(n):
                if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                    continue
                parents[c] = n
                stack.append(c)

    def enclosing_consumer(call: ast.Call) -> bool:
        p = parents.get(call)
        while p is not None:
            if isinstance(p, ast.Call) and _call_name(p) in consuming:
                return True
            p = parents.get(p)
        return False

    # acquisitions → gen / inline-consumed / dropped
    for root in roots:
        nodes = [root] + [n for n in _walk_shallow(root)]
        for n in nodes:
            if not (isinstance(n, ast.Call)
                    and _call_name(n) in reg.acquires):
                continue
            if enclosing_consumer(n):
                continue
            bound = False
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)) \
                    and getattr(stmt, "value", None) is n:
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        facts.gen.add(t.id)
                    # attribute/subscript/tuple target: owned elsewhere
                bound = True
            elif isinstance(stmt, ast.Return):
                bound = True            # ownership moves to the caller
            else:
                p = parents.get(n)
                if isinstance(p, ast.Call) \
                        and isinstance(p.func, ast.Attribute) \
                        and p.func.attr == "append" \
                        and isinstance(p.func.value, ast.Name) \
                        and n in p.args:
                    facts.gen.add(p.func.value.id)
                    bound = True
            if not bound:
                facts.dropped.append((n, _call_name(n)))

    # kills
    for root in roots:
        for n in _walk_shallow(root):
            if isinstance(n, ast.Call) and _call_name(n) in consuming:
                for a in n.args:
                    if isinstance(a, ast.Name):
                        facts.kill.add(a.id)
                    elif isinstance(a, ast.Starred) \
                            and isinstance(a.value, ast.Name):
                        facts.kill.add(a.value.id)
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                facts.kill.add(t.id)    # rebinding ends the obligation
            elif isinstance(t, (ast.Attribute, ast.Subscript)):
                # stored into an owner structure
                facts.kill |= _names_in(stmt.value)
            elif isinstance(t, (ast.Tuple, ast.List)):
                facts.kill |= _target_names(t)
    elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target,
                                                        ast.Name):
        facts.kill.add(stmt.target.id)
    elif isinstance(stmt, ast.Return) and stmt.value is not None:
        facts.kill |= _names_in(stmt.value)
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            facts.kill |= _target_names(t)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        # `for pid in pids: release(pid)` — compensating drain loops:
        # consuming the loop variable consumes the iterable.
        loop_targets = _target_names(stmt.target)
        for n in _walk_shallow(stmt):
            if isinstance(n, ast.Call) and _call_name(n) in consuming:
                args = {a.id for a in n.args if isinstance(a, ast.Name)}
                if args & loop_targets:
                    facts.kill |= _names_in(stmt.iter)
                    break
    return facts


class PageLifetimePass(FlowPass):
    rule = "TPU701"
    name = "page-lifetime"
    description = ("acquired page handle must reach a release/transfer "
                   "on every path out of the function, raise edges "
                   "included")

    def check(self, fc: FlowContext) -> Iterable[Finding]:
        for info in fc.lifetime_fns:
            yield from self._check_fn(info, fc.registry)

    def _check_fn(self, info: FnInfo, reg: ResourceRegistry):
        cfg = build_cfg(info.node)
        n = len(cfg.nodes)
        facts = [_stmt_facts(cfg.nodes[i], reg) for i in range(n)]

        for i in range(n):
            for call, cname in facts[i].dropped:
                yield info.ctx.finding(
                    self.rule, call,
                    f"result of acquire call '{cname}()' is dropped — "
                    f"the page handle can never be released; bind it, "
                    f"or wrap it in a declared transfer",
                    info.qualname)

        # forward may-hold fixpoint
        IN: List[Set[str]] = [set() for _ in range(n)]
        work = list(range(n))
        while work:
            i = work.pop()
            out = (IN[i] - facts[i].kill) | facts[i].gen
            exc_state = IN[i] - facts[i].kill
            for s in cfg.succ[i]:
                if s == EXIT:
                    continue
                edge_out = out - {cfg.edge_null.get((i, s))}
                if not edge_out <= IN[s]:
                    IN[s] |= edge_out
                    work.append(s)
            for s in cfg.exc[i]:
                if s != EXIT and not exc_state <= IN[s]:
                    IN[s] |= exc_state
                    work.append(s)

        # exit-edge audit: earliest origin line per (name, edge kind)
        leaks: Dict[Tuple[str, str], int] = {}
        for i in range(n):
            out = (IN[i] - facts[i].kill) | facts[i].gen
            exc_state = IN[i] - facts[i].kill
            if EXIT in cfg.succ[i]:
                for name in out - {cfg.edge_null.get((i, EXIT))}:
                    key = (name, "return")
                    if key not in leaks or leaks[key] > i:
                        leaks[key] = i
            if EXIT in cfg.exc[i]:
                for name in exc_state:
                    key = (name, "raise")
                    if key not in leaks or leaks[key] > i:
                        leaks[key] = i
        for (name, kind), i in sorted(leaks.items(),
                                      key=lambda kv: (kv[1], kv[0])):
            node = cfg.nodes[i]
            if kind == "raise":
                msg = (f"page handle '{name}' is held across this "
                       f"potentially-raising statement and leaks if it "
                       f"raises (no release/transfer on the exception "
                       f"edge) — add a compensating except/finally "
                       f"that releases it")
            else:
                msg = (f"page handle '{name}' still held when the "
                       f"function exits here — release it, transfer it "
                       f"into a tracked owner, or return it")
            yield info.ctx.finding(self.rule, node, msg, info.qualname)


# ---------------------------------------------------------------------------
# TPU702 — retrace hazard
# ---------------------------------------------------------------------------

class RetraceHazardPass(FlowPass):
    rule = "TPU702"
    name = "retrace-hazard"
    description = ("watched jit entry called with an unbounded python "
                   "scalar, or jitted closure over post-construction "
                   "mutable state — compile-cache growth")

    def check(self, fc: FlowContext) -> Iterable[Finding]:
        reg = fc.registry
        # part A: unbounded python scalars at watched call sites
        for info in fc.graph.fns.values():
            attrs = fc.entry_attrs.get((info.module, info.cls or ""))
            if not attrs:
                continue
            yield from self._check_sites(info, attrs, reg)
        # part B: closures over post-construction-mutated self fields
        writes = self._class_writes(fc)
        for owner, clo in fc.closures:
            written = writes.get((owner.module, owner.cls or ""), {})
            reads = {
                n.attr for n in _walk_shallow(clo)
                if isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "self"
                and isinstance(n.ctx, ast.Load)}
            for attr in sorted(reads & set(written)):
                w_line = written[attr]
                yield owner.ctx.finding(
                    self.rule, clo,
                    f"jitted closure '{clo.name}' reads self.{attr}, "
                    f"which is rebound post-construction (line "
                    f"{w_line}) — every rebind silently retraces; "
                    f"pass it as a traced argument instead",
                    f"{owner.qualname}.{clo.name}")

    # -- part A helpers ------------------------------------------------------
    def _check_sites(self, info: FnInfo, attrs: Set[str],
                     reg: ResourceRegistry):
        len_tainted: Set[str] = set()
        for n in _walk_shallow(info.node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                v = n.value
                if isinstance(v, ast.Call) and _call_name(v) in \
                        reg.bounded_sources:
                    len_tainted.discard(n.targets[0].id)
                elif any(isinstance(c, ast.Call)
                         and _call_name(c) == "len"
                         for c in ast.walk(v)):
                    len_tainted.add(n.targets[0].id)

        def visit(node, loop_vars: Set[str]):
            if node is not info.node \
                    and isinstance(node, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef, ast.Lambda)):
                return
            if isinstance(node, (ast.For, ast.AsyncFor)):
                inner = loop_vars | _target_names(node.target)
                for c in ast.iter_child_nodes(node):
                    visit(c, inner)
                return
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in attrs \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                for a in node.args:
                    reason = self._unbounded(a, loop_vars, len_tainted,
                                             reg)
                    if reason:
                        yield_list.append((node, node.func.attr, reason))
            for c in ast.iter_child_nodes(node):
                visit(c, loop_vars)

        yield_list: List[Tuple[ast.Call, str, str]] = []
        visit(info.node, set())
        for call, attr, reason in yield_list:
            yield info.ctx.finding(
                self.rule, call,
                f"watched jit entry self.{attr}() called with a python "
                f"scalar whose value source is unbounded ({reason}) — "
                f"each distinct value compiles a new executable; "
                f"bucket it or pass an array",
                info.qualname)

    def _unbounded(self, arg, loop_vars: Set[str],
                   len_tainted: Set[str], reg: ResourceRegistry):
        if isinstance(arg, ast.Call):
            nm = _call_name(arg)
            if nm in reg.array_wrappers or nm in reg.bounded_sources:
                return None
        if isinstance(arg, (ast.Constant, ast.Attribute)):
            return None
        for n in [arg] + list(_walk_shallow(arg)):
            if isinstance(n, ast.Call):
                nm = _call_name(n)
                if nm in reg.bounded_sources or nm in reg.array_wrappers:
                    return None         # bounded somewhere in the expr
            if isinstance(n, ast.Call) and _call_name(n) == "len":
                return "len() of a runtime-sized object"
            if isinstance(n, ast.Name):
                if n.id in loop_vars:
                    return f"'{n.id}' is a loop variable"
                if n.id in len_tainted:
                    return f"'{n.id}' is assigned from len()"
        return None

    # -- part B helpers ------------------------------------------------------
    def _class_writes(self, fc: FlowContext):
        """(module, class) → {attr: first post-construction rebind line}."""
        out: Dict[Tuple[str, str], Dict[str, int]] = {}
        ctors = set(fc.registry.ctor_methods)
        want = {(owner.module, owner.cls or "")
                for owner, _ in fc.closures}
        for info in fc.graph.fns.values():
            key = (info.module, info.cls or "")
            if not info.cls or key not in want:
                continue
            if info.qualname.rsplit(".", 1)[-1] in ctors:
                continue
            table = out.setdefault(key, {})
            for n in _walk_shallow(info.node):
                targets = []
                if isinstance(n, ast.Assign):
                    targets = n.targets
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                    targets = [n.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        line = getattr(n, "lineno", 0)
                        if t.attr not in table or table[t.attr] > line:
                            table[t.attr] = line
        return out


# ---------------------------------------------------------------------------
# TPU703 — mirror coherence
# ---------------------------------------------------------------------------

class MirrorCoherencePass(FlowPass):
    rule = "TPU703"
    name = "mirror-coherence"
    description = ("host-side mirror write must co-occur with its "
                   "device op in the same function or a declared "
                   "delegation")

    def check(self, fc: FlowContext) -> Iterable[Finding]:
        for spec in fc.registry.mirrors:
            for info in fc.graph.fns.values():
                if info.module not in spec.modules:
                    continue
                mname = info.qualname.rsplit(".", 1)[-1]
                if mname in spec.ctor_methods:
                    continue
                if f"{info.module}:{info.qualname}" in spec.delegates:
                    continue
                yield from self._check_fn(info, spec)

    def _check_fn(self, info: FnInfo, spec: MirrorSpec):
        host_writes: List[Tuple[ast.AST, str]] = []
        device_ok = False
        for n in _walk_shallow(info.node):
            if isinstance(n, ast.Call) \
                    and _call_name(n) in spec.device_calls:
                device_ok = True
            targets = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets = [n.target]
            for t in targets:
                base = t
                if isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, ast.Attribute):
                    if base.attr in spec.host_attrs:
                        host_writes.append((n, base.attr))
                    if base.attr in spec.device_attrs:
                        device_ok = True
        if not host_writes or device_ok:
            return
        pair_with = ", ".join(sorted(set(spec.device_calls)
                                     | set(spec.device_attrs)))
        seen_lines = set()
        for node, attr in host_writes:
            if node.lineno in seen_lines:
                continue
            seen_lines.add(node.lineno)
            yield info.ctx.finding(
                self.rule, node,
                f"host mirror '{attr}' ({spec.name}) written with no "
                f"paired device op in scope — pair it with one of "
                f"[{pair_with}] or declare a delegation (with reason) "
                f"in flow/resources.py",
                info.qualname)
