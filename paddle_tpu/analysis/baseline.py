"""Baseline suppression file for tpu-lint.

Accepted debt is recorded in ``tools/tpu_lint_baseline.txt`` so the strict
CI run stays green without hiding the rule.  One entry per line::

    RULE  path[::symbol]  # mandatory one-line reason

* ``path`` is repo-relative (posix).
* ``symbol`` is the enclosing def/class qualname as printed by the
  finding (``ReduceOnPlateau.step``); ``*`` (or omitting ``::symbol``)
  baselines the whole file for that rule — used for modules where the
  pattern is the *point* (e.g. paddle's int64 index-output parity).
* The reason is required: an entry without ``#`` is a parse error, so
  nobody can baseline a finding silently.

Entries are matched by (rule, path, symbol) — never by line number, so
unrelated edits to a file do not invalidate its baseline.  Entries that
match nothing are reported as stale so the file shrinks over time.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

__all__ = ["BaselineEntry", "Baseline", "BaselineFormatError"]


class BaselineFormatError(ValueError):
    pass


@dataclasses.dataclass
class BaselineEntry:
    rule: str
    path: str
    symbol: str          # "*" = whole file
    reason: str
    lineno: int = 0
    used: bool = False

    def matches(self, finding) -> bool:
        if finding.rule != self.rule or finding.path != self.path:
            return False
        return self.symbol == "*" or finding.symbol == self.symbol or \
            finding.symbol.startswith(self.symbol + ".")


class Baseline:
    def __init__(self, entries: List[BaselineEntry]):
        self.entries = entries

    @classmethod
    def load(cls, path: Optional[str]) -> "Baseline":
        entries: List[BaselineEntry] = []
        if not path:
            return cls(entries)
        with open(path, "r", encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                line = line.rstrip("\n")
                stripped = line.strip()
                if not stripped or stripped.startswith("#"):
                    continue
                if "#" not in stripped:
                    raise BaselineFormatError(
                        f"{path}:{lineno}: baseline entry needs a "
                        f"'# reason' comment: {stripped!r}")
                spec, reason = stripped.split("#", 1)
                parts = spec.split()
                if len(parts) != 2:
                    raise BaselineFormatError(
                        f"{path}:{lineno}: expected 'RULE path[::symbol]"
                        f"  # reason', got: {stripped!r}")
                rule, target = parts
                if "::" in target:
                    fpath, symbol = target.split("::", 1)
                else:
                    fpath, symbol = target, "*"
                if not reason.strip():
                    raise BaselineFormatError(
                        f"{path}:{lineno}: empty reason for {rule} {target}")
                entries.append(BaselineEntry(rule=rule, path=fpath,
                                             symbol=symbol or "*",
                                             reason=reason.strip(),
                                             lineno=lineno))
        return cls(entries)

    def subset(self, pred) -> "Baseline":
        """Baseline restricted to entries satisfying ``pred`` (entry
        objects are shared, so 'used' marks survive across subsets).  The
        trace tier takes the TPU5xx entries, the concurrency tier the
        TPU6xx ones, and the AST tier everything else — each tier's
        stale report covers only the entries it could ever match, so
        running one tier never flags another tier's debt as stale."""
        return Baseline([e for e in self.entries if pred(e)])

    def matches(self, finding) -> bool:
        hit = False
        for e in self.entries:
            if e.matches(finding):
                e.used = True
                hit = True
        return hit

    def stale(self) -> List[str]:
        return [f"line {e.lineno}: {e.rule} {e.path}::{e.symbol} "
                f"({e.reason})" for e in self.entries if not e.used]
