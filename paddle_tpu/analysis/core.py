"""tpu-lint core — the AST pass framework.

The reference enforces its invariants mechanically: graph passes over the
ProgramDesc (paddle/fluid/framework/ir/pass.h) and a YAML op schema that
drives codegen.  This module is the TPU build's analogue at the Python
source level: a small pass framework that walks every file's AST once,
hands each registered :class:`LintPass` a :class:`FileContext` (parsed
tree + import/alias resolution), and collects :class:`Finding` objects
(rule id + file:line) that CI turns into failures.

Three suppression channels, in priority order:

* inline — ``# tpu-lint: disable=TPU101`` on the offending line;
* baseline — an entry in ``tools/tpu_lint_baseline.txt`` (see
  :mod:`paddle_tpu.analysis.baseline`) carrying a mandatory reason;
* pass scoping — a pass that cannot establish its preconditions (e.g. no
  axis declarations in scope) emits nothing rather than guessing.

See ANALYSIS.md at the repo root for the rule catalogue and how to add a
pass.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Finding", "FileContext", "LintPass", "ProjectPass",
           "ScopedVisitor", "Analyzer", "Report"]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a concrete source location."""

    rule: str          # e.g. "TPU101"
    path: str          # repo-relative posix path
    line: int          # 1-based
    col: int           # 0-based (ast convention)
    message: str
    symbol: str = "<module>"   # qualname of the enclosing def/class

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.symbol}] {self.message}")


_DISABLE_RE = re.compile(r"#\s*tpu-lint:\s*disable=([A-Z0-9, ]+)")


class FileContext:
    """Parsed view of one source file shared by every pass.

    Central services:

    * ``aliases`` — import/alias table: ``jnp`` -> ``jax.numpy``,
      ``ps`` -> ``jax.lax.psum`` (``from jax.lax import psum as ps``).
      Relative imports resolve against the file's package path.
    * ``resolve(node)`` — fully-qualified dotted name of a Name/Attribute
      chain with aliases expanded, or ``None``.
    * ``module_constants`` — module-level ``NAME = "literal"`` string
      assignments (axis-name constants etc.).
    * ``disabled_rules(line)`` — inline suppressions on that line.
    """

    def __init__(self, path: str, root: str):
        self.path = os.path.abspath(path)
        rel = os.path.relpath(self.path, os.path.abspath(root))
        self.relpath = rel.replace(os.sep, "/")
        with open(self.path, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.relpath)
        self.aliases: Dict[str, str] = {}
        self.module_constants: Dict[str, str] = {}
        self._suppress: Dict[int, set] = {}
        self._package = self._package_path()
        self._index()

    # -- construction --------------------------------------------------------
    def _package_path(self) -> str:
        """Dotted package containing this module (from relpath)."""
        parts = self.relpath[:-3].split("/") if self.relpath.endswith(".py") \
            else self.relpath.split("/")
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts[:-1]) if len(parts) > 1 else ""

    def _resolve_relative(self, level: int, module: Optional[str]) -> str:
        base = self._package.split(".") if self._package else []
        # level=1 -> current package, each extra level pops one more
        base = base[:len(base) - (level - 1)] if level - 1 else base
        return ".".join(base + ([module] if module else []))

    def _index(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                mod = self._resolve_relative(node.level, node.module) \
                    if node.level else (node.module or "")
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = \
                        f"{mod}.{a.name}" if mod else a.name
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                self.module_constants[node.targets[0].id] = node.value.value
        for i, line in enumerate(self.lines, 1):
            m = _DISABLE_RE.search(line)
            if m:
                self._suppress[i] = {r.strip() for r in m.group(1).split(",")
                                     if r.strip()}

    # -- services ------------------------------------------------------------
    def resolve(self, node) -> Optional[str]:
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            return f"{base}.{node.attr}" if base else None
        return None

    def resolve_call(self, call: ast.Call) -> Optional[str]:
        return self.resolve(call.func)

    def disabled_rules(self, line: int) -> set:
        return self._suppress.get(line, set())

    def finding(self, rule: str, node, message: str,
                symbol: str = "<module>") -> Finding:
        return Finding(rule=rule, path=self.relpath,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       message=message, symbol=symbol)


class LintPass:
    """Base class for per-file passes.

    ``prepare(contexts)`` runs once with every context in scope (for
    cross-file state like axis declarations); ``check(ctx)`` yields
    findings for one file.
    """

    rule = "TPU000"
    name = "base"
    description = ""

    def prepare(self, contexts: Sequence[FileContext]) -> None:
        pass

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return []


class ProjectPass(LintPass):
    """A pass that runs once per invocation instead of once per file
    (e.g. schema drift: the subject is a generated artifact, not a
    source file)."""

    def check_project(self, root: str,
                      contexts: Sequence[FileContext]) -> Iterable[Finding]:
        return []


class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the enclosing def/class qualname.

    Subclasses read ``self.symbol`` inside any ``visit_*`` and may
    override ``enter_function(node)`` / ``leave_function(node)`` hooks
    (the scope stack is maintained here; do not override
    visit_FunctionDef without calling super).
    """

    def __init__(self):
        self._scope: List[str] = []

    @property
    def symbol(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    def enter_function(self, node):  # hook
        pass

    def leave_function(self, node):  # hook
        pass

    def _visit_scoped(self, node):
        self._scope.append(node.name)
        self.enter_function(node)
        try:
            self.generic_visit(node)
        finally:
            self.leave_function(node)
            self._scope.pop()

    def visit_FunctionDef(self, node):
        self._visit_scoped(node)

    def visit_AsyncFunctionDef(self, node):
        self._visit_scoped(node)

    def visit_ClassDef(self, node):
        self._scope.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._scope.pop()


@dataclasses.dataclass
class Report:
    findings: List[Finding]                  # live, unsuppressed
    baselined: List[Finding]                 # matched a baseline entry
    inline_suppressed: List[Finding]         # # tpu-lint: disable=
    stale_baseline: List[str]                # entries that matched nothing
    errors: List[str]                        # unparsable files etc.
    files: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors

    def summary(self) -> str:
        return (f"{self.files} files, {len(self.findings)} findings, "
                f"{len(self.baselined)} baselined, "
                f"{len(self.inline_suppressed)} inline-suppressed, "
                f"{len(self.stale_baseline)} stale baseline entries")


def _iter_py_files(paths: Sequence[str], root: str) -> List[str]:
    out = []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            out.append(ap)
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


class Analyzer:
    """Run a set of passes over a file tree and fold in suppressions."""

    def __init__(self, root: Optional[str] = None, passes=None,
                 baseline_path: Optional[str] = "auto"):
        from . import ALL_PASSES
        from .baseline import Baseline
        self.root = os.path.abspath(root or os.getcwd())
        self.passes = [p() if isinstance(p, type) else p
                       for p in (passes if passes is not None
                                 else ALL_PASSES)]
        if baseline_path == "auto":
            baseline_path = os.path.join(self.root, "tools",
                                         "tpu_lint_baseline.txt")
            if not os.path.exists(baseline_path):
                baseline_path = None
        base = Baseline.load(baseline_path) if baseline_path \
            else Baseline([])
        # TPU5xx entries belong to the trace tier (analysis.trace),
        # TPU6xx to the concurrency tier (analysis.concurrency) and
        # TPU7xx to the flow tier (analysis.flow) — excluded here so
        # they are never reported stale by an AST run
        self.baseline = base.subset(
            lambda e: not e.rule.startswith(("TPU5", "TPU6", "TPU7")))

    def run(self, paths: Sequence[str]) -> Report:
        report = Report([], [], [], [], [])
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(self.root, p)
            if not os.path.exists(ap):
                # a typo'd path must fail loudly — a silent 0-file run
                # would turn the strict CI gate green while checking nothing
                report.errors.append(f"{p}: path does not exist")
        contexts: List[FileContext] = []
        for path in _iter_py_files(paths, self.root):
            try:
                contexts.append(FileContext(path, self.root))
            except (SyntaxError, UnicodeDecodeError) as e:
                report.errors.append(f"{path}: {e}")
        report.files = len(contexts)

        for pz in self.passes:
            pz.prepare(contexts)
        raw: List[Finding] = []
        for pz in self.passes:
            if isinstance(pz, ProjectPass):
                raw.extend(pz.check_project(self.root, contexts))
            else:
                for ctx in contexts:
                    raw.extend(pz.check(ctx))
        raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        fold_findings(report, raw, contexts, self.baseline)
        return report


def fold_findings(report: Report, raw: Sequence[Finding],
                  contexts: Sequence[FileContext], baseline) -> Report:
    """Classify raw findings into live / inline-suppressed / baselined
    and surface stale baseline entries.  Shared by every tier so the
    suppression semantics cannot drift between them."""
    by_path: Dict[str, FileContext] = {c.relpath: c for c in contexts}
    for f in raw:
        ctx = by_path.get(f.path)
        if ctx is not None and f.rule in ctx.disabled_rules(f.line):
            report.inline_suppressed.append(f)
        elif baseline.matches(f):
            report.baselined.append(f)
        else:
            report.findings.append(f)
    report.stale_baseline = baseline.stale()
    return report
