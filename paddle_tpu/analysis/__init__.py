"""paddle_tpu.analysis — tpu-lint, the static-analysis pass framework.

The reference snapshot polices its 300k-LoC kernel surface with compiler
passes over the ProgramDesc and a generated op schema; this package is
the equivalent gate for the TPU build's Python source: AST passes that
enforce the repo's correctness/perf invariants on every PR *without
compiling a model*.

Rule catalogue (details per pass module, workflow in ANALYSIS.md):

=======  ==================  ==============================================
rule     pass                invariant
=======  ==================  ==============================================
TPU101   host_sync           no device→host sync reachable from jitted code
TPU201   x64                 no f64/s64 widening under the global x64 mode
TPU301   collectives         collective axis names match declared mesh axes
TPU401   schema_drift        ops_schema.yaml matches the live op surface
=======  ==================  ==============================================

A second tier — tpu-audit, TPU5xx — lives in :mod:`.trace` and runs over
the *traced programs* (jaxprs + lowered StableHLO) of the canonical
program registry instead of source text:
``python -m paddle_tpu.analysis --trace --strict``.  See the trace
package docstring for the TPU501-505 catalogue.

A third tier — tpu-race, TPU6xx — lives in :mod:`.concurrency` and runs
over a package-wide call graph closed from the declared thread roots of
the serving stack: ``python -m paddle_tpu.analysis --concurrency
--strict``.  See the concurrency package docstring for TPU601-604.

A fourth tier — tpu-flow, TPU7xx — lives in :mod:`.flow` and runs a
per-function exception-edge dataflow (page lifetimes, retrace hazards,
host/device mirror coherence) over the declared resource registry:
``python -m paddle_tpu.analysis --flow --strict``.  See the flow
package docstring for TPU701-703.

Programmatic use::

    from paddle_tpu.analysis import Analyzer
    report = Analyzer(root=repo_root).run(["paddle_tpu"])
    assert report.ok, "\\n".join(f.format() for f in report.findings)

CLI: ``python -m paddle_tpu.analysis [paths] --strict``.
"""
from .core import (Analyzer, FileContext, Finding, LintPass, ProjectPass,
                   Report, ScopedVisitor)
from .baseline import Baseline, BaselineEntry, BaselineFormatError
from .host_sync import HostSyncPass
from .x64 import S64_COMPUTE_OPS, X64WideningPass
from .collectives import CollectiveAxisPass
from .schema_drift import SchemaDriftPass

from .trace import (TRACE_PASSES, TRACE_RULES, F32_ACCUM_OPS,
                    TraceAnalyzer, TraceProgram)
from .concurrency import (CONCURRENCY_PASSES, CONCURRENCY_RULES,
                          ConcurrencyAnalyzer, DEFAULT_REGISTRY,
                          RoleRegistry)
from .flow import (DEFAULT_FLOW_REGISTRY, FLOW_PASSES, FLOW_RULES,
                   FlowAnalyzer, MirrorSpec, ResourceRegistry)

#: default pass set, in rule-id order.
ALL_PASSES = [HostSyncPass, X64WideningPass, CollectiveAxisPass,
              SchemaDriftPass]

RULES = {p.rule: p for p in ALL_PASSES}

__all__ = ["Analyzer", "FileContext", "Finding", "LintPass", "ProjectPass",
           "Report", "ScopedVisitor", "Baseline", "BaselineEntry",
           "BaselineFormatError", "HostSyncPass", "X64WideningPass",
           "CollectiveAxisPass", "SchemaDriftPass", "ALL_PASSES", "RULES",
           "S64_COMPUTE_OPS", "TRACE_PASSES", "TRACE_RULES",
           "F32_ACCUM_OPS", "TraceAnalyzer", "TraceProgram",
           "CONCURRENCY_PASSES", "CONCURRENCY_RULES", "ConcurrencyAnalyzer",
           "DEFAULT_REGISTRY", "RoleRegistry",
           "DEFAULT_FLOW_REGISTRY", "FLOW_PASSES", "FLOW_RULES",
           "FlowAnalyzer", "MirrorSpec", "ResourceRegistry"]
