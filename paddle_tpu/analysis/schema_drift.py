"""TPU401 — op-schema drift validator.

The reference generates its op surface *from* yaml
(python/paddle/utils/code_gen/api.yaml); this build inverts that and
generates ``ops_schema.yaml`` from the live ``paddle_tpu.ops`` surface
(:mod:`paddle_tpu.ops.schema`).  Either direction, the invariant is the
same: the committed schema and the code must agree.  This project-level
pass regenerates the schema in memory and diffs it against the committed
yaml:

* op in yaml but gone from the live surface — removed/renamed op;
* live op missing from yaml — new op not committed;
* parameter *name* list mismatch for **paddle_tpu-authored ops** —
  signature drift we control.

Pass-through ops (module ``jax.numpy``/``jax.lax``) are only checked for
presence: their parameter lists, defaults, and defining-module paths all
move with the installed jax version (``out_sharding`` appearing on
``matmul``, ``jax.lax`` → ``jax._src.lax.lax``) without changing the op
surface this repo authors, and comparing them would make the gate flap
on every toolchain bump.  ``python -m paddle_tpu.ops.schema`` refreshes
the committed file when the surface really changes.

Findings anchor to the op's line in ops_schema.yaml so the fix location
is one click away.
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Sequence, Tuple

from .core import FileContext, Finding, ProjectPass

RULE = "TPU401"

_OP_RE = re.compile(r"^- name: (\S+)$")
_PARAM_RE = re.compile(r"^  - \{name: ([^,}]+)")


def parse_schema_yaml(path: str) -> Dict[str, Tuple[int, List[str]]]:
    """Parse the generator's own minimal-YAML dialect:
    op name -> (line number, [param names])."""
    ops: Dict[str, Tuple[int, List[str]]] = {}
    current = None
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.rstrip("\n")
            m = _OP_RE.match(line)
            if m:
                current = m.group(1)
                ops[current] = (lineno, [])
                continue
            m = _PARAM_RE.match(line)
            if m and current is not None:
                ops[current][1].append(m.group(1).strip())
    return ops


class SchemaDriftPass(ProjectPass):
    rule = RULE
    name = "op-schema-drift"
    description = ("ops_schema.yaml out of sync with the live "
                   "paddle_tpu.ops surface")

    def __init__(self, schema_path: str = None):
        self._schema_path = schema_path

    def check_project(self, root: str,
                      contexts: Sequence[FileContext]) -> List[Finding]:
        path = self._schema_path or os.path.join(root, "ops_schema.yaml")
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        if not os.path.exists(path):
            return []   # nothing committed to validate against
        try:
            from ..ops.schema import generate_schema
            live = {op["name"]: ([p["name"] for p in op["params"]],
                                 str(op.get("module", "")))
                    for op in generate_schema()}
        except Exception as e:   # import failure = env problem, not drift
            return [Finding(RULE, rel, 1, 0,
                            f"could not introspect live op surface: {e}",
                            "<schema>")]
        committed = parse_schema_yaml(path)
        regen = ("stale ops_schema.yaml — regenerate with "
                 "`python -m paddle_tpu.ops.schema`")
        findings: List[Finding] = []
        for name, (line, params) in sorted(committed.items()):
            if name not in live:
                findings.append(Finding(
                    RULE, rel, line, 0,
                    f"op {name!r} is in the schema but not on the live "
                    f"paddle_tpu.ops surface; {regen}", "<schema>"))
            elif params != live[name][0] \
                    and live[name][1].startswith("paddle_tpu"):
                findings.append(Finding(
                    RULE, rel, line, 0,
                    f"op {name!r} params drifted: schema has "
                    f"{params}, live signature has {live[name][0]}; {regen}",
                    "<schema>"))
        for name in sorted(set(live) - set(committed)):
            findings.append(Finding(
                RULE, rel, 1, 0,
                f"live op {name!r} missing from the schema; {regen}",
                "<schema>"))
        return findings
