"""The canonical-program registry for the trace-tier audit.

The audit is only as strong as the set of programs it sees, so the
registry pins the repo's compiled entry points the way
``tests/analysis_fixtures/`` pins AST shapes:

* ``gpt_train_step`` — TrainStep fwd+bwd+update on ``GPTConfig.tiny``
  (the program the x64 HLO audit already compiles; donation declared on
  params/buffers/opt_state);
* ``pipeline_1f1b`` — the shard_map'd 1F1B step with an SGD update over a
  ('pp',) mesh (``paddle_tpu.distributed.pipeline.canonical_1f1b_step``);
* ``gpt_decode`` — the model-level one-token decode step over the STATIC
  slotted KV cache (prefill eagerly, trace the cached decode);
* ``serving/*`` — the serving engine's compiled entries for BOTH cache
  layouts: the paged decode step, chunked prefill, and page
  copy-on-write (pool buffers donated — TPU502 checks the aliasing
  materializes) plus the slotted decode step and bucketed prefill kept
  for A/B;
* ``pallas/<family>/<variant>`` — every registered Pallas kernel variant,
  traced at the bench-standard key in bf16 (``bf16_region`` metadata set,
  so TPU501 audits the variants' f32 usage against F32_ACCUM_OPS).

Builders are lazy and isolated: a builder that cannot run in this
environment (e.g. too few devices for the pipeline mesh) raises
:class:`ProgramSkip` and is reported as a skip, not a failure — but an
unexpectedly *broken* builder is an operational error that fails the CLI,
because a silently-empty registry would turn the strict gate green while
auditing nothing.
"""
from __future__ import annotations

import fnmatch
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .core import TraceProgram

__all__ = ["ProgramSkip", "register_builder", "build_programs",
           "builder_names"]


class ProgramSkip(RuntimeError):
    """Raised by a builder whose preconditions this environment lacks."""


def _ensure_virtual_devices(n: int = 8):
    """Best-effort XLA_FLAGS default for embedders who call
    :func:`build_programs` before anything initialized the jax backend.
    It CANNOT help the CLI or tests: ``import paddle_tpu`` already
    initializes the backend, so by the time this runs the flag is a
    no-op there — the CLI must be launched with shell-level
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (CI does;
    tests get it from conftest.py).  Builders that then find too few
    devices skip, and the CLI reports the skip as a loud warning with
    the fix."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d" % n
        ).strip()


#: name -> (builder, name-prefix of every program it emits).  A single
#: logical entry point may expand to many programs (the kernel variants);
#: the prefix lets pattern-filtered runs skip builders that cannot match
#: BEFORE paying their trace/lower cost.
_BUILDERS: Dict[str, Tuple[Callable[[], List[TraceProgram]], str]] = {}


def register_builder(name: str, prefix: Optional[str] = None):
    def deco(fn):
        _BUILDERS[name] = (fn, prefix if prefix is not None else name)
        return fn
    return deco


def _pattern_may_match(prefix: str, pattern: str) -> bool:
    """Conservative pre-filter: can ``pattern`` possibly match a name
    starting with ``prefix``?  Compares the pattern's literal head (up to
    its first wildcard) against the prefix — over-approximates (never
    skips a builder whose programs could match)."""
    import re
    literal = re.split(r"[*?\[]", pattern, 1)[0]
    return literal.startswith(prefix) or prefix.startswith(literal)


def builder_names() -> List[str]:
    return sorted(_BUILDERS)


def _donate_labels(args) -> Dict[int, str]:
    """{flat input index: tree-path label} for a jitted entry's argument
    tuple — makes TPU502 findings name the parameter, not an index."""
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tuple(args))
    return {i: "args" + jax.tree_util.keystr(kp)
            for i, (kp, _v) in enumerate(flat)}


@register_builder("gpt_train_step")
def _build_gpt_train_step() -> List[TraceProgram]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

    paddle.seed(0)
    cfg = GPTConfig.tiny()
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(parameters=model.parameters(),
                                 learning_rate=1e-3)
    step = TrainStep(model, lambda lo, la: crit(lo, la), opt)
    x = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 32)).astype(np.int32))
    args = step.trace_args((x, x))
    # keep_unused=True for the AUDIT wrap only: the production step prunes
    # unused inputs (e.g. the rng key when every dropout prob is 0), which
    # would misalign the lowered entry's argument indices against the
    # jaxpr's donation flags
    audit_step = jax.jit(step._step_fn,
                         donate_argnums=step._donate_argnums,
                         keep_unused=True)
    jaxpr = jax.make_jaxpr(audit_step)(*args)
    lowered = audit_step.lower(*args)
    return [TraceProgram(
        name="gpt_train_step", jaxpr=jaxpr,
        lowered_text=lowered.as_text(), lowered=lowered,
        meta={"kind": "train_step", "mesh_axes": {},
              "donate_labels": _donate_labels(args)})]


@register_builder("pipeline_1f1b")
def _build_pipeline_1f1b() -> List[TraceProgram]:
    import jax

    from paddle_tpu.distributed.pipeline import (
        PipelinePreconditionError, canonical_1f1b_step)

    try:
        jitted, args, meta = canonical_1f1b_step()
    except PipelinePreconditionError as e:
        # ONLY the environment precondition is a skip; any other failure
        # propagates into the errors list and fails the strict CLI
        raise ProgramSkip(str(e))
    jaxpr = jax.make_jaxpr(jitted)(*args)
    lowered = jitted.lower(*args)
    meta = dict(meta)
    meta["donate_labels"] = _donate_labels(args)
    return [TraceProgram(name="pipeline_1f1b", jaxpr=jaxpr,
                         lowered_text=lowered.as_text(), lowered=lowered,
                         meta=meta)]


@register_builder("gpt_decode")
def _build_gpt_decode() -> List[TraceProgram]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit import functional_call
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig.tiny()
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt = Tensor(jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (1, 8)).astype(np.int32)))
    # eager prefill fills the STATIC slotted cache (a registered pytree —
    # it crosses the jit boundary directly); the traced program is the
    # model-level per-token cached decode, whose shape no longer depends
    # on how many tokens were generated
    _logits, cache = model(prompt, cache=model.gen_cache(1, max_len=64))
    state = model.functional_state()

    def decode_step(state, x, cache):
        (logits, new_cache), _ = functional_call(
            model, state, Tensor(x), cache=cache)
        return logits, new_cache

    x1 = jnp.asarray(np.full((1, 1), 7, np.int32))
    jitted = jax.jit(decode_step)
    jaxpr = jax.make_jaxpr(jitted)(state, x1, cache)
    lowered = jitted.lower(state, x1, cache)
    return [TraceProgram(
        name="gpt_decode", jaxpr=jaxpr, lowered_text=lowered.as_text(),
        lowered=lowered, meta={"kind": "decode", "mesh_axes": {}})]


@register_builder("serving", prefix="serving/")
def _build_serving() -> List[TraceProgram]:
    """The serving engine's compiled entry points at a tiny config, BOTH
    cache layouts:

    * paged (the default) — ``serving/decode_step`` (the batched,
      donation-aliased continuous-batching iteration over the page
      pool; TPU502 verifies the pool donation actually materializes as
      input/output aliasing), ``serving/prefill_chunk`` (the single
      chunked-prefill program), ``serving/cow_copy`` (the page
      copy-on-write step, both pool buffers donated), and the
      disaggregated handoff pair (ISSUE 15) — ``serving/kv_export``
      (page gather into the dense transfer buffer; TPU502 confirms the
      TRANSFER-BUFFER donation materializes, the buffer is reused every
      chunk) and ``serving/kv_import`` (scatter into the decode pool;
      pool donated);
    * slotted (kept for A/B) — ``serving/decode_step_slotted`` and
      ``serving/prefill`` (the smallest bucket);
    * ISSUE 8 modes, COMPOSED (int8 KV + speculative) so the audit
      covers the quantized scatter/gather and the in-program
      accept/rollback — ``serving/spec_verify`` (the batched k+1-token
      verify over the int8 pool; code AND scale pools donated) and
      ``serving/decode_step_q8`` (the single-token fallback on the same
      engine)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving.engine import DecodeEngine

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig.tiny())
    paged = DecodeEngine(model, num_slots=2, max_len=64, page_size=16)
    slotted = DecodeEngine(model, num_slots=2, max_len=64, paged=False)
    spec_q8 = DecodeEngine(model, num_slots=2, max_len=64, page_size=16,
                           spec_k=4, kv_dtype="int8")
    out: List[TraceProgram] = []
    for name, fn, donate, args in (
            ("serving/decode_step", paged._decode_fn,
             paged._decode_donate_argnums, paged.decode_trace_args()),
            ("serving/prefill_chunk", paged._prefill_chunk_fn,
             paged._prefill_chunk_donate_argnums,
             paged.prefill_chunk_trace_args()),
            ("serving/cow_copy", paged._cow_fn,
             paged._cow_donate_argnums, paged.cow_trace_args()),
            ("serving/kv_export", paged._kv_export_fn,
             paged._kv_export_donate_argnums,
             paged.kv_export_trace_args()),
            ("serving/kv_import", paged._kv_import_fn,
             paged._kv_import_donate_argnums,
             paged.kv_import_trace_args()),
            ("serving/decode_step_slotted", slotted._decode_fn,
             slotted._decode_donate_argnums, slotted.decode_trace_args()),
            ("serving/prefill", slotted._prefill_fn,
             slotted._prefill_donate_argnums,
             slotted.prefill_trace_args()),
            ("serving/spec_verify", spec_q8._verify_fn,
             spec_q8._verify_donate_argnums,
             spec_q8.verify_trace_args()),
            ("serving/decode_step_q8", spec_q8._decode_fn,
             spec_q8._decode_donate_argnums,
             spec_q8.decode_trace_args())):
        # keep_unused=True for the AUDIT wrap only (same rationale as the
        # train step): pruning would misalign the entry's argument
        # indices against the jaxpr's donation flags.  x64_scope(False)
        # matches the production trace scope (engine.prefill/decode) so
        # the audited program is the program that runs.
        from paddle_tpu.core.dtype import x64_scope
        audit = jax.jit(fn, donate_argnums=donate, keep_unused=True)
        with x64_scope(False):
            jaxpr = jax.make_jaxpr(audit)(*args)
            lowered = audit.lower(*args)
        out.append(TraceProgram(
            name=name, jaxpr=jaxpr, lowered_text=lowered.as_text(),
            lowered=lowered,
            meta={"kind": "serving", "mesh_axes": {},
                  "donate_labels": _donate_labels(args)}))
    return out


@register_builder("serving_tp", prefix="serving/")
def _build_serving_tp() -> List[TraceProgram]:
    """The tensor-parallel sharded twins (ISSUE 12): the SAME serving
    entry fns jitted with the tp=2 engine's in/out shardings on a
    2-device ('mp',) CPU mesh — composed int8 + speculative, so TPU502
    confirms the code AND scale pool donations materialize as per-shard
    input/output aliasing, and TPU503's SPMD checks audit the lowered
    num_partitions and the partitioned program's collectives.  Skips
    (loudly, like the pipeline builder) when the backend has fewer than
    2 devices — the CLI must run under shell-level
    ``XLA_FLAGS=--xla_force_host_platform_device_count`` (CI does)."""
    import jax

    if len(jax.devices()) < 2:
        raise ProgramSkip(
            "tensor-parallel serving programs need >= 2 devices; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count before "
            "the backend initializes")

    import paddle_tpu as paddle
    from paddle_tpu.core.dtype import x64_scope
    from paddle_tpu.distributed import mesh as _mesh
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving.engine import DecodeEngine

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig.tiny())
    eng = DecodeEngine(model, num_slots=2, max_len=64, page_size=16,
                       tp=2, spec_k=4, kv_dtype="int8")
    mesh_axes = {ax: int(eng.mesh.shape[ax]) for ax in eng.mesh.axis_names}
    out: List[TraceProgram] = []
    for name, entry, fn, donate, args in (
            ("serving/decode_step_tp", "serving.decode",
             eng._decode_fn, eng._decode_donate_argnums,
             eng.decode_trace_args()),
            ("serving/prefill_chunk_tp", "serving.prefill_chunk",
             eng._prefill_chunk_fn, eng._prefill_chunk_donate_argnums,
             eng.prefill_chunk_trace_args()),
            ("serving/spec_verify_tp", "serving.spec_verify",
             eng._verify_fn, eng._verify_donate_argnums,
             eng.verify_trace_args())):
        ins, outs = eng._entry_shardings[entry]
        # keep_unused + the production shardings: the audited program is
        # the sharded program that runs (see the `serving` builder for
        # the keep_unused/donation-alignment rationale)
        audit = jax.jit(fn, donate_argnums=donate, keep_unused=True,
                        in_shardings=ins, out_shardings=outs)
        with x64_scope(False), _mesh.mesh_scope(eng.mesh):
            jaxpr = jax.make_jaxpr(audit)(*args)
            lowered = audit.lower(*args)
        out.append(TraceProgram(
            name=name, jaxpr=jaxpr, lowered_text=lowered.as_text(),
            lowered=lowered,
            meta={"kind": "serving", "mesh_axes": mesh_axes,
                  "spmd_sharded": True,
                  "donate_labels": _donate_labels(args)}))
    return out


@register_builder("serving_overlap", prefix="serving/")
def _build_serving_overlap() -> List[TraceProgram]:
    """The decomposed-collective twins (ISSUE 20): the SAME tp=2 entries
    as the ``serving_tp`` builder, built with ``overlap_comm=True`` so
    the monolithic all-gather/all-to-all lowering is replaced by the
    ppermute rings.  Registering both lets TPU502 confirm the overlap
    rewrite preserves the donation aliasing and TPU503 audit the
    partitioned program the overlapped engine actually runs — and the
    structural zero-monolithic-all-gather test reads these programs'
    ``collective_stats`` by-kind split."""
    import jax

    if len(jax.devices()) < 2:
        raise ProgramSkip(
            "overlapped tensor-parallel serving programs need >= 2 "
            "devices; set XLA_FLAGS=--xla_force_host_platform_"
            "device_count before the backend initializes")

    import paddle_tpu as paddle
    from paddle_tpu.core.dtype import x64_scope
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving.engine import DecodeEngine

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig.tiny())
    eng = DecodeEngine(model, num_slots=2, max_len=64, page_size=16,
                       tp=2, spec_k=4, kv_dtype="int8",
                       overlap_comm=True)
    mesh_axes = {ax: int(eng.mesh.shape[ax]) for ax in eng.mesh.axis_names}
    out: List[TraceProgram] = []
    for name, entry, fn, donate, args in (
            ("serving/decode_step_tp_overlap", "serving.decode",
             eng._decode_fn, eng._decode_donate_argnums,
             eng.decode_trace_args()),
            ("serving/prefill_chunk_tp_overlap", "serving.prefill_chunk",
             eng._prefill_chunk_fn, eng._prefill_chunk_donate_argnums,
             eng.prefill_chunk_trace_args()),
            ("serving/spec_verify_tp_overlap", "serving.spec_verify",
             eng._verify_fn, eng._verify_donate_argnums,
             eng.verify_trace_args())):
        ins, outs = eng._entry_shardings[entry]
        audit = jax.jit(fn, donate_argnums=donate, keep_unused=True,
                        in_shardings=ins, out_shardings=outs)
        # _entry_scope pins the engine's resolved overlap switch around
        # the trace exactly as the production retrace path does
        with x64_scope(False), eng._entry_scope():
            jaxpr = jax.make_jaxpr(audit)(*args)
            lowered = audit.lower(*args)
        out.append(TraceProgram(
            name=name, jaxpr=jaxpr, lowered_text=lowered.as_text(),
            lowered=lowered,
            meta={"kind": "serving", "mesh_axes": mesh_axes,
                  "spmd_sharded": True, "overlap_comm": True,
                  "donate_labels": _donate_labels(args)}))
    return out


@register_builder("pallas_kernels", prefix="pallas/")
def _build_pallas_kernels() -> List[TraceProgram]:
    import jax

    from paddle_tpu.kernels import autotune as at

    at._import_kernel_families()
    out: List[TraceProgram] = []
    for fam_name, key in at.standard_keys():
        fam = at.families().get(fam_name)
        if fam is None or fam.traceable is None:
            continue
        # audit at bf16 regardless of host platform: the TPU production
        # dtype is what TPU501's bf16-region rule is about, and tracing
        # executes nothing, so the host backend doesn't matter
        key = dict(key, dtype="bfloat16")
        seen = set()
        for cand in fam.candidates(key):
            variant = cand.get("variant", "base")
            if variant in seen:
                continue   # one program per VARIANT; block-size siblings
            seen.add(variant)        # lower the same kernel structure
            fn, args = fam.traceable(cand, key)
            jaxpr = jax.make_jaxpr(fn)(*args)

            def lower_thunk(fn=fn, args=args):
                # on-demand lowering for cost extraction (the audit
                # passes stay jaxpr-level): off-chip this prices the
                # interpret-mode lowering, which the cost CLI labels
                return jax.jit(fn).lower(*args)

            out.append(TraceProgram(
                name="pallas/%s/%s" % (fam_name, variant), jaxpr=jaxpr,
                lower_thunk=lower_thunk,
                meta={"kind": "pallas_kernel", "bf16_region": True,
                      "mesh_axes": {}, "family": fam_name,
                      "variant": variant, "autotune_key": at.key_str(key)}))
    if not out:
        raise ProgramSkip("no kernel families expose traceables")
    return out


def build_programs(patterns: Optional[Sequence[str]] = None
                   ) -> Tuple[List[TraceProgram], List[str], List[str]]:
    """Build the registry (optionally fnmatch-filtered by program name).

    Returns ``(programs, skipped, errors)`` — ``skipped`` are builders
    whose environment preconditions failed (reported, non-fatal);
    ``errors`` are broken builders (fatal under the CLI: an empty audit
    must not look green).
    """
    _ensure_virtual_devices()
    programs: List[TraceProgram] = []
    skipped: List[str] = []
    errors: List[str] = []
    for name in builder_names():
        builder, prefix = _BUILDERS[name]
        if patterns and not any(_pattern_may_match(prefix, pat)
                                for pat in patterns):
            continue  # no pattern can match this builder's programs —
            # skip its trace/lower cost entirely ('pallas/*' runs must
            # not pay for the GPT train-step lowering)
        try:
            built = builder()
        except ProgramSkip as e:
            skipped.append("%s: %s" % (name, e))
            continue
        except Exception as e:
            errors.append("builder %s failed: %s: %s"
                          % (name, type(e).__name__, e))
            continue
        programs.extend(built)
    if patterns:
        programs = [p for p in programs
                    if any(fnmatch.fnmatch(p.name, pat)
                           for pat in patterns)]
    return programs, skipped, errors
