"""TPU501 — bf16-region f32-upcast leak detection.

The f32 analogue of the s64 HLO audit (tests/test_x64_audit.py +
rule TPU201): in a program whose compute is declared bf16 (the flash/CE/LN
kernel variants traced at bf16, AMP regions), f32 is the *statistics and
accumulator* dtype — softmax max/sum chains, lse, variance, the optimizer
masters.  An f32 **compute** chain that re-materializes activations in
f32 — a transcendental activation (tanh/erf/logistic) applied to an
upcast, or a matmul fed f32-converted bf16 operands instead of bf16
operands with f32 accumulation — silently doubles VPU lane pressure and
HBM traffic in exactly the regions the bf16 variants exist to slim.

Mechanically: every ``convert_element_type`` bf16→f32 equation must feed
only primitives in :data:`F32_ACCUM_OPS` (the allowlist is shared at
``paddle_tpu.analysis.F32_ACCUM_OPS`` the way ``S64_COMPUTE_OPS`` is
shared between TPU201 and the runtime HLO audit, so the static and
runtime vocabularies cannot diverge).  A consumer outside the allowlist —
an MXU op or a transcendental — is the leak signal.

Scoping: consumers are resolved within the upcast's own jaxpr scope; a
value escaping into a subjaxpr is accounted to the call primitive
(``scan``/``cond``/``pjit`` are allowlisted — the subjaxpr's own converts
are audited in their own scope).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

from ..core import Finding
from .core import OpPathCounter, TracePass, TraceProgram, subjaxprs

__all__ = ["F32_ACCUM_OPS", "DtypeLeakPass"]

#: primitives allowed to consume a bf16→f32 upcast inside a bf16 region —
#: the statistics/accumulator vocabulary.  Reductions and running stats,
#: the softmax/lse chain (exp/log/sub/max against stats), normalization
#: (div/mul/rsqrt/sqrt by stats), structural/layout ops (free), compares,
#: select, and the call primitives whose bodies are audited separately.
#: NOT here — and therefore the leak signal: ``dot_general`` / conv (use
#: bf16 operands with ``preferred_element_type=f32`` accumulation), and
#: the transcendental activations (tanh/erf/logistic/pow/sin/cos...) that
#: re-run whole activation tensors on the f32 VPU path.
F32_ACCUM_OPS = frozenset({
    # reductions / accumulators
    "reduce_sum", "reduce_max", "reduce_min", "add_any", "cumsum",
    "cumlogsumexp", "argmax", "argmin",
    # softmax / lse statistic chain
    "exp", "exp2", "log", "log1p", "expm1", "sub", "add", "max", "min",
    "mul", "div", "neg", "abs", "sign",
    # normalization stats
    "rsqrt", "sqrt", "square", "integer_pow",
    # structural / layout (free at any dtype)
    "broadcast_in_dim", "reshape", "transpose", "slice", "squeeze",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "rev", "select_n", "gather", "convert_element_type", "copy",
    "stop_gradient", "clamp",
    # comparisons (produce bool)
    "lt", "le", "gt", "ge", "eq", "ne", "is_finite",
    # call primitives — bodies audited in their own scope
    "scan", "while", "cond", "pjit", "closed_call", "core_call",
    "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
    "remat", "checkpoint", "shard_map", "pallas_call", "named_call",
})

_BF16 = "bfloat16"
_F32 = "float32"


def _scope_consumers(jaxpr) -> Dict[int, List[str]]:
    """id(var) -> consuming primitive names within one jaxpr scope (a use
    as a scope output counts as the pseudo-consumer "output", which is
    always allowed — returning f32 stats is the point)."""
    cons: Dict[int, List[str]] = {}
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if hasattr(v, "aval"):
                cons.setdefault(id(v), []).append(eqn.primitive.name)
    for v in jaxpr.outvars:
        if hasattr(v, "aval"):
            cons.setdefault(id(v), []).append("output")
    return cons


class DtypeLeakPass(TracePass):
    """TPU501: no f32 compute leaks inside declared-bf16 regions."""

    rule = "TPU501"
    name = "dtype_leak"
    description = ("bf16-region bf16->f32 upcasts feed only the shared "
                   "statistics/accumulator allowlist (F32_ACCUM_OPS)")

    def check(self, program: TraceProgram) -> Iterable[Finding]:
        if not program.meta.get("bf16_region") or program.jaxpr is None:
            return
        yield from self._check_jaxpr(
            program, getattr(program.jaxpr, "jaxpr", program.jaxpr),
            OpPathCounter())

    def _check_jaxpr(self, program, jaxpr, counter) -> Iterable[Finding]:
        cons = _scope_consumers(jaxpr)
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            path = counter.path_for(eqn)
            if prim == "convert_element_type":
                src = eqn.invars[0]
                src_dt = str(getattr(getattr(src, "aval", None), "dtype",
                                     ""))
                dst_dt = str(eqn.params.get("new_dtype", ""))
                if src_dt == _BF16 and dst_dt == _F32:
                    bad = sorted({
                        c for c in cons.get(id(eqn.outvars[0]), [])
                        if c not in F32_ACCUM_OPS and c != "output"})
                    if bad:
                        yield self.finding(
                            program, path,
                            "bf16->f32 upcast consumed by non-accumulator "
                            "op%s %s — keep the chain bf16 (f32 is for "
                            "statistics/accumulators; matmuls should take "
                            "bf16 operands with preferred_element_type="
                            "f32)" % ("s" if len(bad) > 1 else "",
                                      ", ".join(bad)))
            for _tag, sub in subjaxprs(eqn):
                yield from self._check_jaxpr(program, sub, counter)
