"""tpu-audit core — the jaxpr/StableHLO trace-tier pass framework.

tpu-lint (the AST tier, :mod:`paddle_tpu.analysis.core`) polices what the
*source text* shows; this tier polices what the *compiler sees*: passes run
over the jaxprs and lowered StableHLO of a checked-in registry of canonical
programs (:mod:`.programs` — the GPT TrainStep fwd+bwd, the 1F1B pipeline
step, the KV-cache decode artifact, every registered Pallas kernel
variant).  A missed buffer donation, an f32 upcast inside a bf16 region or
a VMEM-overflowing block layout are all invisible to the AST but mechanical
to detect here.

The tier reuses tpu-lint's reporting machinery wholesale: findings are
:class:`~paddle_tpu.analysis.core.Finding` objects whose ``path`` is the
**program name** and whose ``symbol`` is a stable **op-path** (name-stack +
primitive + ordinal), so ``tools/tpu_lint_baseline.txt`` entries key on
``(rule, program, op-path)`` exactly like the AST tier keys on
``(rule, file, qualname)`` — one baseline file, one reason-required format,
one stale-entry report.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, \
    Sequence, Tuple

from ..core import Finding, Report

__all__ = ["TraceProgram", "TracePass", "TraceAnalyzer", "walk_eqns",
           "op_paths", "subjaxprs", "EqnSite", "OpPathCounter"]


@dataclasses.dataclass
class TraceProgram:
    """One canonical program under audit.

    * ``jaxpr`` — the ClosedJaxpr of the traced entry (outermost; passes
      recurse through pjit/shard_map/cond/scan/while/pallas_call).
    * ``lowered_text`` — StableHLO of the lowered entry when the program
      has one (kernels are audited at the jaxpr level only).
    * ``lowered`` — the ``jax.stages.Lowered`` object itself when the
      builder lowered one (the text above is derived from it): TPU506
      and the cost CLI compile it for XLA cost/memory analysis.
    * ``lower_thunk`` — zero-arg callable producing a Lowered for
      programs kept at the jaxpr level (Pallas kernel variants), so
      cost extraction can lower on demand without the registry paying
      30+ lowerings up front on every audit run.
    * ``meta`` — program facts the passes check against:
        ``donated_invars``   tuple of bools per flat entry input
        ``donate_labels``    {flat input index: human label} for findings
        ``mesh_axes``        {axis name: size} declared for the program
        ``bf16_region``      True when compute is declared bf16 (TPU501)
        ``allow_callbacks``  True to exempt host callbacks (TPU505)
        ``hbm_budget``       per-program peak-HBM budget bytes (TPU506;
                             overrides the pass's declared table)
        ``kind``             "train_step" | "pipeline" | "decode" |
                             "pallas_kernel" | "fixture"
    """

    name: str
    jaxpr: Any
    lowered_text: Optional[str] = None
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    lowered: Any = None
    lower_thunk: Optional[Callable[[], Any]] = None


@dataclasses.dataclass(frozen=True)
class EqnSite:
    """One jaxpr equation with its stable op-path."""

    eqn: Any
    path: str            # e.g. "transformer/attn/dot_general.1"
    depth: int
    parent: Optional[Any]  # the enclosing call-like eqn (pjit/scan/...)


def subjaxprs(eqn) -> List[Tuple[str, Any]]:
    """(param name, Jaxpr) pairs nested under one equation, in param order.
    Understands ClosedJaxpr wrappers and list/tuple-valued params
    (``cond``'s branches)."""
    out: List[Tuple[str, Any]] = []
    for pname, val in eqn.params.items():
        vals = val if isinstance(val, (list, tuple)) else [val]
        for i, v in enumerate(vals):
            inner = getattr(v, "jaxpr", v)
            if hasattr(inner, "eqns") and hasattr(inner, "invars"):
                tag = pname if len(vals) == 1 else "%s[%d]" % (pname, i)
                out.append((tag, inner))
    return out


def _name_stack(eqn) -> str:
    try:
        return str(eqn.source_info.name_stack)
    except Exception:
        return ""


class OpPathCounter:
    """THE op-path assignment for the trace tier: every pass and
    :func:`walk_eqns` share this one implementation, because baseline
    entries and fixture pins key on the exact string — a second copy that
    drifted would silently stop matching accepted debt.

    Paths are ``<name-stack>/<primitive>.<ordinal>`` where the ordinal
    counts prior equations with the same (name-stack, primitive) anywhere
    in the program (in deterministic depth-first eqns-then-subjaxprs
    order) — stable under unrelated edits, pinnable in fixtures and
    baselines.  One counter instance per program walk.
    """

    def __init__(self):
        self._counts: Dict[Tuple[str, str], int] = {}

    def path_for(self, eqn) -> str:
        prim = eqn.primitive.name
        stack = _name_stack(eqn)
        key = (stack, prim)
        n = self._counts.get(key, 0)
        self._counts[key] = n + 1
        return "%s/%s.%d" % (stack, prim, n) if stack \
            else "%s.%d" % (prim, n)


def walk_eqns(closed_jaxpr, *, into_pallas: bool = True
              ) -> Iterator[EqnSite]:
    """Depth-first walk over every equation of a (Closed)Jaxpr, recursing
    through call-like primitives, with :class:`OpPathCounter` paths."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    counter = OpPathCounter()

    def rec(jx, depth, parent):
        for eqn in jx.eqns:
            path = counter.path_for(eqn)
            yield EqnSite(eqn=eqn, path=path, depth=depth, parent=parent)
            if eqn.primitive.name == "pallas_call" and not into_pallas:
                continue
            for _tag, sub in subjaxprs(eqn):
                yield from rec(sub, depth + 1, eqn)

    yield from rec(jaxpr, 0, None)


def op_paths(closed_jaxpr) -> List[str]:
    return [site.path for site in walk_eqns(closed_jaxpr)]


class TracePass:
    """Base class for trace-tier passes: ``check(program)`` yields findings
    for one :class:`TraceProgram`.  ``prepare(programs)`` runs once with
    the full registry in scope."""

    rule = "TPU500"
    name = "trace-base"
    description = ""

    def prepare(self, programs: Sequence[TraceProgram]) -> None:
        pass

    def check(self, program: TraceProgram) -> Iterable[Finding]:
        return []

    # -- shared helper -------------------------------------------------------
    def finding(self, program: TraceProgram, op_path: str,
                message: str, line: int = 0) -> Finding:
        return Finding(rule=self.rule, path=program.name, line=line, col=0,
                       message=message, symbol=op_path)


class TraceAnalyzer:
    """Run trace passes over a program set and fold in the baseline.

    Mirrors :class:`paddle_tpu.analysis.core.Analyzer`, but the unit of
    analysis is a program, not a file; only TPU5xx baseline entries apply
    (the AST tier symmetrically ignores them), so running one tier never
    reports the other tier's baseline as stale.
    """

    def __init__(self, root: Optional[str] = None, passes=None,
                 baseline_path: Optional[str] = "auto"):
        import os
        from . import TRACE_PASSES
        from ..baseline import Baseline
        self.root = os.path.abspath(root or os.getcwd())
        self.passes = [p() if isinstance(p, type) else p
                       for p in (passes if passes is not None
                                 else TRACE_PASSES)]
        if baseline_path == "auto":
            baseline_path = os.path.join(self.root, "tools",
                                         "tpu_lint_baseline.txt")
            if not os.path.exists(baseline_path):
                baseline_path = None
        base = Baseline.load(baseline_path) if baseline_path else Baseline([])
        self.baseline = base.subset(lambda e: e.rule.startswith("TPU5"))

    def run(self, programs: Sequence[TraceProgram],
            errors: Sequence[str] = (), partial: bool = False) -> Report:
        report = Report([], [], [], [], list(errors))
        report.files = len(programs)
        # ``partial=True`` (a pattern-filtered CLI run) scopes the
        # baseline to the audited programs so entries for un-built ones
        # are not falsely reported stale.  Full runs keep the whole
        # baseline: they are the authority on genuinely-dead entries
        # (e.g. a renamed program), which must keep surfacing so the
        # file shrinks over time.
        baseline = self.baseline
        if partial:
            names = {p.name for p in programs}
            baseline = baseline.subset(lambda e: e.path in names)
        for pz in self.passes:
            pz.prepare(programs)
        raw: List[Finding] = []
        for pz in self.passes:
            for prog in programs:
                try:
                    raw.extend(pz.check(prog))
                except Exception as e:  # a crashed pass must fail loudly,
                    report.errors.append(   # not silently skip its rule
                        "%s on %s: %s: %s" % (pz.rule, prog.name,
                                              type(e).__name__, e))
        raw.sort(key=lambda f: (f.path, f.symbol, f.rule))
        for f in raw:
            if baseline.matches(f):
                report.baselined.append(f)
            else:
                report.findings.append(f)
        report.stale_baseline = baseline.stale()
        return report
