"""TPU502 — donation audit: declared donations must materialize as
input-output aliasing in the lowered program.

``donate_argnums`` is a *request*: XLA only aliases a donated input onto
an output of identical shape/dtype/layout.  When a refactor breaks the
match — an output dtype drifts (fp32 master -> bf16 param), an output is
dropped, a tree reorders — jax silently downgrades the donation to a
warning-at-dispatch and the program holds BOTH buffers live: peak HBM for
the step state **doubles** with zero functional signal.  On the GPT
configs that is the difference between fitting and OOM.

Mechanically: the lowered StableHLO entry (``func.func public @main``)
carries ``tf.aliasing_output = N`` on every input argument whose donation
materialized; a flat input that the jaxpr declares donated
(``donated_invars`` on the pjit equation, or the registry's recorded
metadata) but whose entry argument carries no aliasing attribute is a
donation miss.  Findings are keyed by the flat input's tree label
(``in[3]:params/linear.weight``) so baselines survive unrelated
signature growth.
"""
from __future__ import annotations

import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..core import Finding
from .core import TracePass, TraceProgram, walk_eqns

__all__ = ["DonationPass", "parse_entry_aliasing", "declared_donations"]

_MAIN_RE = re.compile(
    r"func\.func\s+(?:public\s+)?@main\s*\((?P<args>.*?)\)\s*->"
    r"(?P<results>[^\n]*)",
    re.S)
#: attrs are brace-delimited but may CONTAIN braces inside quoted
#: strings — a sharded entry's arguments carry
#: ``mhlo.sharding = "{devices=[...]<=[N]}"`` ahead of
#: ``tf.aliasing_output`` (ISSUE 12), and a naive ``[^}]*`` stops at the
#: quoted ``}`` and silently drops every attribute after the sharding,
#: reporting materialized donations as misses on exactly the sharded
#: entries the audit was extended to cover
_ARG_RE = re.compile(
    r"%arg(?P<idx>\d+):\s*(?P<type>(?:tensor|!stablehlo\.token)[^{,)]*)"
    r"(?:\{(?P<attrs>(?:\"[^\"]*\"|[^{}\"])*)\})?")
_TYPE_RE = re.compile(r"tensor<[^>]+>")


def parse_entry_aliasing(lowered_text: str
                         ) -> Optional[Dict[int, Dict[str, Any]]]:
    """{flat input index: {"aliased", "donor", "type", "result_match"}}
    parsed from the StableHLO entry signature, or None when no @main is
    found.

    jax emits two spellings of a live donation: ``tf.aliasing_output``
    when it paired input and output itself (single-device path), and
    ``jax.buffer_donor`` when pairing is deferred to XLA (GSPMD path) —
    for the latter the statically-checkable invariant is that a
    type-compatible output EXISTS for the donor.  *No attribute at all*
    on a declared-donated input means jax dropped the donation at
    lowering: the silent miss this pass exists to catch."""
    m = _MAIN_RE.search(lowered_text)
    if not m:
        return None
    result_types = _TYPE_RE.findall(m.group("results"))
    out: Dict[int, Dict[str, Any]] = {}
    for am in _ARG_RE.finditer(m.group("args")):
        attrs = am.group("attrs") or ""
        ty = am.group("type").strip()
        out[int(am.group("idx"))] = {
            "aliased": "tf.aliasing_output" in attrs,
            "donor": "jax.buffer_donor" in attrs,
            "type": ty,
            "result_match": ty in result_types,
        }
    return out


def declared_donations(program: TraceProgram) -> Optional[Tuple[bool, ...]]:
    """Per-flat-input donation flags: the registry's recorded metadata
    first, else the ``donated_invars`` of the outermost pjit equation."""
    meta = program.meta.get("donated_invars")
    if meta is not None:
        return tuple(bool(b) for b in meta)
    if program.jaxpr is None:
        return None
    for site in walk_eqns(program.jaxpr):
        if site.depth == 0 and site.eqn.primitive.name == "pjit":
            di = site.eqn.params.get("donated_invars")
            if di is not None and any(di):
                return tuple(bool(b) for b in di)
    return None


class DonationPass(TracePass):
    """TPU502: every declared donation aliases an output in the lowering."""

    rule = "TPU502"
    name = "donation"
    description = ("declared donate_argnums materialize as input-output "
                   "aliasing (tf.aliasing_output) in the lowered entry")

    def check(self, program: TraceProgram) -> Iterable[Finding]:
        donated = declared_donations(program)
        if not donated or not any(donated):
            return
        text = program.lowered_text
        if not text:
            return  # jaxpr-only programs (kernels) carry no entry to audit
        entry = parse_entry_aliasing(text)
        if entry is None:
            yield self.finding(
                program, "entry",
                "program declares donations but its lowered text has no "
                "@main entry to audit")
            return
        labels = program.meta.get("donate_labels", {})
        if len(entry) != len(donated):
            # keep_unused=False dropped inputs: indices no longer align
            # 1:1 with the jaxpr's invars.  Refuse to guess — a misaligned
            # audit could baseline the wrong parameter forever.
            yield self.finding(
                program, "entry",
                "cannot align donation flags with the lowered entry: %d "
                "jaxpr inputs vs %d entry arguments (keep_unused "
                "pruning?) — re-register the program with used inputs"
                % (len(donated), len(entry)))
            return
        for i, don in enumerate(donated):
            if not don:
                continue
            info = entry.get(i, {"aliased": False, "donor": False,
                                 "type": "?", "result_match": False})
            if info["aliased"]:
                continue
            if info["donor"] and info["result_match"]:
                continue  # GSPMD path: XLA pairs it; a matching output
            label = labels.get(i) or labels.get(str(i)) or ""
            sym = "in[%d]%s" % (i, ":" + label if label else "")
            if info["donor"]:
                yield self.finding(
                    program, sym,
                    "donated input %d%s is marked jax.buffer_donor but NO "
                    "output shares its type %s — XLA cannot pair it and "
                    "the donation will be dropped at compile; peak HBM "
                    "holds both copies"
                    % (i, " (%s)" % label if label else "", info["type"]))
            else:
                yield self.finding(
                    program, sym,
                    "donated input %d%s does not alias any output in the "
                    "lowering — the donation silently failed (shape/dtype "
                    "drift between the donated buffer and every output?); "
                    "peak HBM holds both copies"
                    % (i, " (%s)" % label if label else ""))
