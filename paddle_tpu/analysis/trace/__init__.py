"""paddle_tpu.analysis.trace — tpu-audit, the jaxpr/StableHLO tier.

Second tier of the analysis framework: where tpu-lint (TPU1xx-4xx) walks
Python ASTs, this tier walks the **traced program** — jaxprs and lowered
StableHLO of the canonical-program registry (:mod:`.programs`) — and
enforces the invariants source text cannot show.

=======  =================  =============================================
rule     pass               invariant
=======  =================  =============================================
TPU501   dtype_leak         bf16-region f32 upcasts feed only the shared
                            statistics/accumulator allowlist
TPU502   donation           declared donate_argnums materialize as
                            input-output aliasing in the lowered entry
TPU503   collective_order   identical collective sequence on all cond
                            branches; collective axes declared with
                            consistent sizes; ppermute perms in range
TPU504   vmem_budget        Pallas BlockSpec working set fits per-core
                            VMEM (also gates autotune candidates
                            pre-compile)
TPU505   purity             no dead/duplicated expensive subcomputation,
                            no stray host callbacks
TPU506   hbm_budget         compiled peak-HBM (XLA memory_analysis
                            derived bound) fits the declared per-program
                            budget — TPU504's post-compile complement
=======  =================  =============================================

CLI: ``python -m paddle_tpu.analysis --trace [--select TPU504] --strict``.
Baseline entries share ``tools/tpu_lint_baseline.txt`` keyed on
``(rule, program, op-path)``.
"""
from .core import (EqnSite, TraceAnalyzer, TracePass, TraceProgram,
                   op_paths, subjaxprs, walk_eqns)
from .dtype_leak import F32_ACCUM_OPS, DtypeLeakPass
from .donation import DonationPass
from .collective_order import COLLECTIVE_PRIMS, CollectiveOrderPass
from .vmem import (VMEM_LIMIT_BYTES, VMEM_RESERVE_BYTES, KernelFootprint,
                   VmemBudgetPass, fits_vmem, footprint_of_callable,
                   pallas_footprints)
from .purity import CALLBACK_PRIMS, EXPENSIVE_PRIMS, PurityPass
from .hbm_budget import HBM_BUDGETS, HbmBudgetPass
from .programs import ProgramSkip, build_programs, builder_names

#: default trace pass set, in rule-id order.
TRACE_PASSES = [DtypeLeakPass, DonationPass, CollectiveOrderPass,
                VmemBudgetPass, PurityPass, HbmBudgetPass]

TRACE_RULES = {p.rule: p for p in TRACE_PASSES}

__all__ = ["TraceProgram", "TracePass", "TraceAnalyzer", "EqnSite",
           "walk_eqns", "op_paths", "subjaxprs",
           "DtypeLeakPass", "DonationPass", "CollectiveOrderPass",
           "VmemBudgetPass", "PurityPass", "HbmBudgetPass", "HBM_BUDGETS",
           "F32_ACCUM_OPS", "COLLECTIVE_PRIMS", "CALLBACK_PRIMS",
           "EXPENSIVE_PRIMS", "VMEM_LIMIT_BYTES", "VMEM_RESERVE_BYTES",
           "KernelFootprint", "pallas_footprints", "footprint_of_callable",
           "fits_vmem", "ProgramSkip", "build_programs", "builder_names",
           "TRACE_PASSES", "TRACE_RULES"]
