"""TPU504 — static VMEM-budget estimation for Pallas kernels.

Every Pallas kernel's per-core working set is statically determined by its
BlockSpecs: Mosaic keeps one ``block_shape`` tile per input/output operand
resident in VMEM (double-buffered whenever the grid revisits the buffer,
which is the common case), plus every ``pltpu.VMEM`` scratch allocation in
full.  A candidate whose tiles don't fit the ~16 MiB per-core VMEM faults
*on device* — after a TPU session was already burned on tracing, compiling
and shipping it.  This module reads the exact same ``grid_mapping`` the
compiler consumes (off the traced ``pallas_call`` equation) and prices the
working set up front, so:

* the **TPU504 pass** audits every registered kernel-variant program in
  the canonical registry, and
* :func:`paddle_tpu.kernels.autotune.tune` rejects unfittable candidates
  **before compile** (they show up as ``rejected: vmem`` in the timing
  table instead of faulting mid-warm).

The model is deliberately a *budget*, not a simulator: operands mapped to
``ANY`` memory stay in HBM (their kernels DMA chunks through explicit
scratch, which IS counted), index/scalar-prefetch operands live in SMEM,
and a safety reserve is held back for Mosaic's own spills/semaphores.
Overestimating by a tile is fine; underestimating wastes a TPU session.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterable, List, Optional

from ..core import Finding
from .core import TracePass, TraceProgram, walk_eqns

__all__ = ["VMEM_LIMIT_BYTES", "VMEM_RESERVE_BYTES", "KernelFootprint",
           "pallas_footprints", "footprint_of_callable", "fits_vmem",
           "VmemBudgetPass"]

#: per-core VMEM on the supported TPU generations (v4/v5e/v5p all carry
#: 16 MiB per TensorCore; PERF.md's measured overflow at s=8192 confirms
#: the kernels are budgeted against this number).  Overridable for future
#: parts via PADDLE_TPU_VMEM_LIMIT_MB.
VMEM_LIMIT_BYTES = int(float(os.environ.get("PADDLE_TPU_VMEM_LIMIT_MB",
                                            "16")) * 1024 * 1024)

#: held back for Mosaic-managed temporaries, semaphores and register
#: spills — the compiler's own working set that BlockSpecs don't show.
VMEM_RESERVE_BYTES = 1024 * 1024


class KernelFootprint:
    """Static VMEM price of one ``pallas_call``."""

    def __init__(self, name: str, op_path: str):
        self.name = name
        self.op_path = op_path
        self.operand_bytes = 0      # double-buffered block tiles
        self.scratch_bytes = 0      # explicit VMEM scratch, counted once
        self.detail: List[str] = []

    @property
    def total_bytes(self) -> int:
        return self.operand_bytes + self.scratch_bytes

    def fits(self, limit: Optional[int] = None,
             reserve: Optional[int] = None) -> bool:
        limit = VMEM_LIMIT_BYTES if limit is None else limit
        reserve = VMEM_RESERVE_BYTES if reserve is None else reserve
        return self.total_bytes <= max(0, limit - reserve)

    def summary(self) -> str:
        return ("%s: %.0f KiB blocks + %.0f KiB scratch = %.0f KiB "
                "(limit %.0f KiB - %.0f KiB reserve)"
                % (self.name, self.operand_bytes / 1024,
                   self.scratch_bytes / 1024, self.total_bytes / 1024,
                   VMEM_LIMIT_BYTES / 1024, VMEM_RESERVE_BYTES / 1024))


def _block_elems(block_shape) -> int:
    """Product of a BlockSpec block shape; non-int entries (mapped /
    squeezed dims) occupy one element along that axis."""
    n = 1
    for dim in block_shape:
        n *= dim if isinstance(dim, int) else 1
    return n


def _scratch_bytes(eqn, num_scratch: int) -> (int, List[str]):
    """Price the kernel's explicit scratch from the trailing invars of the
    kernel jaxpr (their avals carry shape/dtype; semaphores and SMEM refs
    price to ~0 — they are not VMEM tiles)."""
    total, detail = 0, []
    if not num_scratch:
        return total, detail
    kernel_jaxpr = getattr(eqn.params.get("jaxpr"), "jaxpr",
                           eqn.params.get("jaxpr"))
    if kernel_jaxpr is None:
        return total, detail
    for var in kernel_jaxpr.invars[-num_scratch:]:
        aval = getattr(var, "aval", None)
        if aval is None:
            continue
        space = str(getattr(aval, "memory_space", "")).lower()
        dtype = getattr(aval, "dtype", None)
        shape = getattr(aval, "shape", ())
        if dtype is None or "semaphore" in str(dtype).lower() \
                or "semaphore" in space:
            continue
        if "smem" in space:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        b = n * dtype.itemsize
        total += b
        detail.append("scratch%s %s = %d B" % (tuple(shape), dtype, b))
    return total, detail


def pallas_footprints(closed_jaxpr, name: str = "<program>"
                      ) -> List[KernelFootprint]:
    """Footprint of every ``pallas_call`` reachable in a (Closed)Jaxpr."""
    out = []
    for site in walk_eqns(closed_jaxpr, into_pallas=False):
        if site.eqn.primitive.name != "pallas_call":
            continue
        gm = site.eqn.params.get("grid_mapping")
        if gm is None:
            continue
        fp = KernelFootprint(name, site.path)
        # grid of extent 1 is visited once — no pipelining, single buffer
        grid = getattr(gm, "grid", ())
        multi_step = 1
        for g in grid:
            multi_step *= int(g) if isinstance(g, int) else 2
        dbuf = 2 if multi_step > 1 else 1
        for bm in gm.block_mappings:
            block = getattr(bm, "block_shape", None)
            aval = getattr(bm, "array_shape_dtype", None)
            if block is None or aval is None:
                continue
            space = str(getattr(bm, "block_aval", "")).lower()
            if "memoryspace.any" in space or "<any>" in space:
                # ANY-space operand: stays in HBM, DMA'd via counted scratch
                continue
            b = _block_elems(block) * aval.dtype.itemsize * dbuf
            fp.operand_bytes += b
            fp.detail.append("block%s %s x%d = %d B"
                             % (tuple(block), aval.dtype, dbuf, b))
        sb, sdetail = _scratch_bytes(site.eqn,
                                     getattr(gm, "num_scratch_operands", 0))
        fp.scratch_bytes += sb
        fp.detail.extend(sdetail)
        out.append(fp)
    return out


def footprint_of_callable(fn, *example_args) -> List[KernelFootprint]:
    """Trace ``fn`` abstractly (ShapeDtypeStructs work; nothing executes,
    nothing compiles) and price its pallas_calls.  The autotuner's
    pre-compile gate."""
    import jax
    jaxpr = jax.make_jaxpr(fn)(*example_args)
    return pallas_footprints(jaxpr)


def fits_vmem(fn, *example_args) -> (bool, str):
    """(fits, human reason) for every pallas_call in ``fn``."""
    fps = footprint_of_callable(fn, *example_args)
    for fp in fps:
        if not fp.fits():
            return False, fp.summary()
    return True, ""


class VmemBudgetPass(TracePass):
    """TPU504: every Pallas kernel program's static block+scratch working
    set fits the per-core VMEM budget."""

    rule = "TPU504"
    name = "vmem_budget"
    description = ("Pallas BlockSpec working set (double-buffered blocks + "
                   "VMEM scratch) fits per-core VMEM")

    def check(self, program: TraceProgram) -> Iterable[Finding]:
        if program.jaxpr is None:
            return
        for fp in pallas_footprints(program.jaxpr, program.name):
            if not fp.fits():
                yield self.finding(
                    program, fp.op_path,
                    "VMEM budget exceeded: %s" % fp.summary())
