"""TPU505 — dead/duplicated subcomputation + stray host-callback audit.

Three program hygiene invariants at the jaxpr level:

* **dead subcomputation** — an effect-free equation whose every output is
  unused in its scope.  jax does not DCE at trace time, so work a
  refactor orphaned (a loss term no longer returned, a residual nobody
  consumes) silently rides along into every compile; XLA usually drops
  it, but the trace/compile time is paid forever and an *effectful* dead
  op (or one behind a custom call boundary) ships to the device.  Only
  expensive primitives fire (matmuls, convs, reductions, scans, kernel
  calls) — dead converts/broadcasts are routine tracing artifacts.
* **duplicated subcomputation** — two equations in one scope with the
  same primitive, same inputs and same parameters: a CSE miss at the
  program level (XLA's CSE runs per-fusion and misses cross-region
  duplicates, e.g. a re-computed lse that the bwd already receives as a
  residual).  Same expensive-primitive scoping.
* **stray host callback** — ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` (``jax.debug.print``) in a production program
  force a device→host round-trip per step; a leftover debug print in the
  train step is a silent multi-ms stall.  Programs that legitimately
  call back (registered with ``allow_callbacks``) are exempt.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Tuple

from ..core import Finding
from .core import OpPathCounter, TracePass, TraceProgram, subjaxprs

__all__ = ["EXPENSIVE_PRIMS", "CALLBACK_PRIMS", "PurityPass"]

#: primitives worth flagging when dead or duplicated (cheap layout ops
#: are routine tracing artifacts and stay exempt).
EXPENSIVE_PRIMS = frozenset({
    "dot_general", "conv_general_dilated", "reduce_sum", "reduce_max",
    "reduce_min", "reduce_prod", "cumsum", "cumlogsumexp", "sort",
    "scatter", "scatter-add", "gather", "scan", "while", "pjit",
    "pallas_call", "custom_vjp_call", "custom_jvp_call", "shard_map",
    "exp", "log", "tanh", "erf", "logistic", "rsqrt",
})

CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "debug_print",
    "host_callback_call", "outside_call",
})


def _is_drop(var) -> bool:
    # DropVar repr is "_"; isinstance check kept duck-typed so the pass
    # survives jax moving the class between core modules
    return type(var).__name__ == "DropVar" or repr(var) == "_"


def _param_sig(params: Dict[str, Any]) -> str:
    """Hashable parameter signature excluding jaxpr-valued params (eqns
    with subjaxprs are excluded from duplicate detection anyway)."""
    items = []
    for k in sorted(params):
        v = params[k]
        if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
            return ""  # not comparable
        items.append("%s=%r" % (k, v))
    return ";".join(items)


class PurityPass(TracePass):
    """TPU505: no dead/duplicated expensive work, no stray callbacks."""

    rule = "TPU505"
    name = "purity"
    description = ("no dead or duplicated expensive subcomputations, no "
                   "stray host callbacks in the traced program")

    def check(self, program: TraceProgram) -> Iterable[Finding]:
        if program.jaxpr is None:
            return
        jaxpr = getattr(program.jaxpr, "jaxpr", program.jaxpr)
        yield from self._scope(program, jaxpr, OpPathCounter())

    def _scope(self, program, jaxpr, counter) -> Iterable[Finding]:
        used = set()
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                if hasattr(v, "aval"):
                    used.add(id(v))
        for v in jaxpr.outvars:
            if hasattr(v, "aval"):
                used.add(id(v))

        seen: Dict[Tuple, str] = {}
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            path = counter.path_for(eqn)

            if prim in CALLBACK_PRIMS \
                    and not program.meta.get("allow_callbacks"):
                cb = eqn.params.get("callback")
                yield self.finding(
                    program, path,
                    "host callback %s%s in a production program — forces "
                    "a device->host round-trip every step (leftover "
                    "debug hook?)"
                    % (prim, " (%s)" % cb if cb is not None else ""))

            effects = getattr(eqn, "effects", None)
            # tracing erases the user-code/artifact distinction (an unused
            # result becomes a DropVar either way), so every effect-free
            # expensive eqn with no live output fires; KNOWN artifacts of
            # jax's own machinery (e.g. the softmax custom_jvp primal
            # re-trace in the train step) are baselined with reasons —
            # that is exactly what (rule, program, op-path) keys are for
            dead = (not effects
                    and all(_is_drop(v) or id(v) not in used
                            for v in eqn.outvars))
            if dead and prim in EXPENSIVE_PRIMS:
                yield self.finding(
                    program, path,
                    "dead subcomputation: %s result is never used in its "
                    "scope — orphaned work rides into every compile"
                    % prim)

            has_sub = bool(subjaxprs(eqn))
            if prim in EXPENSIVE_PRIMS and not has_sub and not dead:
                psig = _param_sig(eqn.params)
                invar_sig = tuple(
                    id(v) if hasattr(v, "aval") else repr(v)
                    for v in eqn.invars)
                dup_key = (prim, invar_sig, psig)
                if dup_key in seen:
                    yield self.finding(
                        program, path,
                        "duplicated subcomputation: identical %s (same "
                        "inputs, same parameters) already computed at %s "
                        "— CSE miss, compute it once and reuse"
                        % (prim, seen[dup_key]))
                else:
                    seen[dup_key] = path

            for _tag, sub in subjaxprs(eqn):
                yield from self._scope(program, sub, counter)
