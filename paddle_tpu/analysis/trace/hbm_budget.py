"""TPU506 — compiled peak-HBM vs a declared per-program budget.

TPU504 prices a Pallas kernel's VMEM working set *before* compile; this
pass is its post-compile HBM complement: the canonical registry's
programs are compiled (off their stored ``lowered`` entries — nothing
re-traces) and XLA's own ``memory_analysis()`` yields the derived peak
buffer bound ``argument + output + temp - alias``
(:func:`paddle_tpu.observability.costs.report_from_compiled`).  A
program whose name appears in :data:`HBM_BUDGETS` (or whose meta
declares ``hbm_budget``) must fit its budget — so a perf PR that
silently doubles a serving entry's peak HBM fails the audit at the
program that regressed, instead of OOMing a chip three sessions later.

Budget discipline:

* budgets are **per program as registered** (the registry's tiny
  configs), sized ~1.6x the measured peak at declaration time — tight
  enough that a 2x regression can NEVER sail through, loose enough for
  backend layout jitter (the derived peak excludes generated-code
  bytes, the one wildly backend-dependent term);
* a declared budget that cannot be priced is a **finding, not a skip**:
  a program that lost its lowered entry (or stopped compiling) would
  otherwise turn the gate silently green;
* programs without a budget are not findings — declare budgets
  deliberately, starting with the serving entries (the multi-hundred-MB
  pools at production scale are exactly where a silent 2x hurts most).

``meta["hbm_budget"]`` overrides the table (fixtures use this).
"""
from __future__ import annotations

from typing import Dict, Iterable

from ..core import Finding
from .core import TracePass, TraceProgram

__all__ = ["HBM_BUDGETS", "HbmBudgetPass"]

#: {program name: peak-HBM budget bytes} for the canonical registry.
#: Sized ~1.6x the measured CPU-audit peak at declaration (ISSUE 11):
#: decode_step 603,330 B / prefill_chunk 764,788 B / spec_verify
#: 598,498 B / cow_copy 139,288 B — re-measure with
#: ``python -m paddle_tpu.observability programs`` when resizing.
HBM_BUDGETS: Dict[str, int] = {
    "serving/decode_step": 1_000_000,
    "serving/prefill_chunk": 1_250_000,
    "serving/spec_verify": 1_000_000,
    "serving/cow_copy": 250_000,
}


class HbmBudgetPass(TracePass):
    """TPU506: every budgeted program's compiled peak-HBM (derived
    argument+output+temp-alias bound) fits its declared budget."""

    rule = "TPU506"
    name = "hbm_budget"
    description = ("compiled peak-HBM (XLA memory_analysis, derived "
                   "arg+out+temp-alias bound) fits the declared "
                   "per-program budget")

    #: the op-path symbol findings key on: the check is whole-program,
    #: so one stable pseudo-path keeps baseline entries pinnable
    SYMBOL = "memory/peak_bytes"

    def check(self, program: TraceProgram) -> Iterable[Finding]:
        budget = program.meta.get("hbm_budget",
                                  HBM_BUDGETS.get(program.name))
        if budget is None:
            return
        from ...observability import costs as _costs
        report = _costs.report_for_program(program)
        if not report.available:
            # loud by design: a budgeted program that cannot be priced
            # (lost its lowered entry, stopped compiling) must not turn
            # the gate silently green
            yield self.finding(
                program, self.SYMBOL,
                "HBM budget %d B declared but the program cannot be "
                "priced on this backend: %s" % (budget, report.note))
            return
        if report.peak_bytes is None:
            # LOUD, same as unpriceable: a budget was DECLARED for this
            # program, so a memory_analysis that reports no buffer
            # sizes (e.g. a jax upgrade renaming the fields) must not
            # turn the gate silently green — CPU and TPU both report
            # today, so this finding means extraction broke, not that
            # the program regressed
            yield self.finding(
                program, self.SYMBOL,
                "HBM budget %d B declared but memory_analysis reports "
                "no buffer sizes on this backend (cost extraction "
                "broke, or the backend genuinely lacks the analysis — "
                "either way the declared budget is unenforceable and "
                "must not look green)" % budget)
            return
        if report.peak_bytes > budget:
            yield self.finding(
                program, self.SYMBOL,
                "peak HBM %d B exceeds the declared budget %d B "
                "(argument %s + output %s + temp %s - alias %s; budgets "
                "live in analysis/trace/hbm_budget.py and are sized "
                "~1.6x the measured peak — a regression this large is a "
                "real allocation change, not jitter)"
                % (report.peak_bytes, budget, report.argument_bytes,
                   report.output_bytes, report.temp_bytes,
                   report.alias_bytes))
