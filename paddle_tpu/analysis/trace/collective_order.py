"""TPU503 — collective-order and axis safety inside traced programs.

The deadlock class the AST tier's TPU301 cannot prove: collectives on TPU
are *rendezvous* ops — every participant of an axis must issue the same
collective sequence.  A ``lax.cond`` whose branches issue different
collective sequences deadlocks the fleet the first time the predicate
diverges across devices (and XLA will not stop you).  Likewise a
collective over an axis the program's mesh never declared, or a
``ppermute`` whose permutation indexes outside the axis extent, is a
guaranteed runtime failure that only shows up once a real multi-chip job
is already running.

Three mechanical checks over the jaxpr (recursing through pjit /
shard_map / scan / while bodies):

* **branch parity** — every ``cond`` has the identical ordered collective
  signature ``(primitive, axes)`` on all branches;
* **axis membership** — every named axis used by a collective is declared
  by the program's mesh (registry metadata or the enclosing ``shard_map``
  equation's mesh param), and any ``shard_map`` mesh agrees with the
  declared axis sizes;
* **permutation bounds** — ``ppermute`` pairs stay inside the axis size.

Scoping: collectives inside ``while`` bodies are checked for axis
membership but not trip-count uniformity (data-dependent trip counts are
undecidable statically); positional (int) axes are hardware-anonymous and
skipped.

**SPMD-sharded entries (ISSUE 12).**  GSPMD programs (jit with in/out
shardings — the serving engine's tensor-parallel twins) carry no
collective *primitives* in their jaxpr: XLA's partitioner inserts the
collectives at compile time, which would make the three jaxpr checks
vacuously green on exactly the programs that go multi-chip first.
Programs whose meta declares ``spmd_sharded: True`` therefore get two
extra mechanical checks:

* the lowered module's ``mhlo.num_partitions`` must equal the declared
  mesh's device product (a registered sharded entry whose jit shardings
  quietly used a different mesh is a trace/deployment mismatch);
* the COMPILED (post-partitioning) HLO must contain collective
  instructions at all — a "sharded" entry whose partitioned program
  moves no data was silently replicated, the sharding never happened —
  and every collective's ``replica_groups`` must be well-formed over the
  partition count (ids in range, disjoint uniform groups whose size
  divides the mesh product): a malformed group is the GSPMD-era
  equivalent of a collective over an undeclared axis.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..core import Finding
from .core import OpPathCounter, TracePass, TraceProgram, subjaxprs

__all__ = ["COLLECTIVE_PRIMS", "CollectiveOrderPass"]

#: rendezvous collectives (axis_index is per-device arithmetic, not a
#: rendezvous — excluded on purpose).
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter", "pgather",
})


def _named_axes(eqn) -> Tuple[str, ...]:
    """String axis names a collective equation rendezvouses over."""
    params = eqn.params
    raw = params.get("axes", params.get("axis_name", ()))
    if not isinstance(raw, (tuple, list)):
        raw = (raw,)
    return tuple(a for a in raw if isinstance(a, str))


def _collective_signature(jaxpr) -> Tuple[Tuple[str, Tuple[str, ...]], ...]:
    """Ordered (primitive, axes) sequence of every collective reachable in
    a jaxpr, depth-first — the rendezvous schedule a device executes."""
    sig: List[Tuple[str, Tuple[str, ...]]] = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            sig.append((eqn.primitive.name, _named_axes(eqn)))
        for _tag, sub in subjaxprs(eqn):
            sig.extend(_collective_signature(sub))
    return tuple(sig)


def _mesh_axes_of(eqn) -> Optional[Dict[str, int]]:
    mesh = eqn.params.get("mesh")
    if mesh is None:
        return None
    try:
        return dict(zip(mesh.axis_names, mesh.devices.shape))
    except Exception:
        try:
            return dict(mesh.shape)
        except Exception:
            return None


class CollectiveOrderPass(TracePass):
    """TPU503: uniform collective schedules, declared axes, legal perms."""

    rule = "TPU503"
    name = "collective_order"
    description = ("identical collective sequence on all cond branches; "
                   "collective axes declared by the mesh with consistent "
                   "sizes; ppermute permutations in range")

    def check(self, program: TraceProgram) -> Iterable[Finding]:
        declared = dict(program.meta.get("mesh_axes", {}) or {})
        if program.jaxpr is not None:
            jaxpr = getattr(program.jaxpr, "jaxpr", program.jaxpr)
            yield from self._walk(program, jaxpr, declared,
                                  OpPathCounter())
        if program.meta.get("spmd_sharded"):
            yield from self._check_spmd(program, declared)

    # -- GSPMD-sharded entries (ISSUE 12) ----------------------------------

    #: stable pseudo-paths for the whole-program SPMD findings
    SPMD_SYMBOL = "spmd/num_partitions"
    SPMD_COLL_SYMBOL = "spmd/partitioned_collectives"

    def _check_spmd(self, program, declared) -> Iterable[Finding]:
        import re
        n = 1
        for v in declared.values():
            n *= int(v)
        text = program.lowered_text or ""
        m = re.search(r"mhlo\.num_partitions\s*=\s*(\d+)", text)
        got = int(m.group(1)) if m else None
        if got != n:
            yield self.finding(
                program, self.SPMD_SYMBOL,
                "sharded entry lowered with num_partitions=%s but the "
                "declared mesh (%s) has %d devices — the registered "
                "shardings and the declared topology disagree"
                % (got, declared or "{}", n))
            return
        if n <= 1:
            return
        # the partitioned program: compile off the stored lowered entry
        # (cached on program.meta — TPU506 and the cost CLI share it)
        from ...observability import costs as _costs
        try:
            compiled = _costs.compile_program(program)
        except Exception as e:
            yield self.finding(
                program, self.SPMD_COLL_SYMBOL,
                "sharded entry failed to compile for the partitioned-"
                "collective audit: %s: %s — an unverifiable sharded "
                "program must not look green" % (type(e).__name__, e))
            return
        try:
            hlo = compiled.as_text() if compiled is not None else None
        except Exception:
            hlo = None
        # ONE collective-instruction scan for the whole repo: the same
        # op list / async-pair rules price the serving.collective_bytes
        # counter — a second copy here would drift
        stats = (None if compiled is None
                 else _costs.collective_stats(compiled))
        if stats is None or not isinstance(hlo, str) or not hlo:
            yield self.finding(
                program, self.SPMD_COLL_SYMBOL,
                "backend exposes no compiled HLO text — the partitioned-"
                "collective audit cannot run on a program that DECLARES "
                "spmd_sharded, and must not look green")
            return
        if stats["ops"] == 0:
            yield self.finding(
                program, self.SPMD_COLL_SYMBOL,
                "declared sharded over %d devices but the partitioned "
                "program contains NO collective instructions — the "
                "sharding silently never materialized (a head-partitioned "
                "decode must at least psum its row-parallel projections)"
                % n)
            return
        for groups in self._replica_groups(hlo):
            flat = [d for g in groups for d in g]
            sizes = {len(g) for g in groups}
            bad = None
            if any(d < 0 or d >= n for d in flat):
                bad = "device ids outside [0, %d)" % n
            elif len(set(flat)) != len(flat):
                bad = "overlapping groups"
            elif len(sizes) != 1:
                bad = "non-uniform group sizes %s" % sorted(sizes)
            elif n % next(iter(sizes)):
                bad = ("group size %d does not divide the mesh's %d "
                       "devices" % (next(iter(sizes)), n))
            if bad:
                yield self.finding(
                    program, self.SPMD_COLL_SYMBOL,
                    "malformed replica_groups %s in the partitioned "
                    "program: %s" % (groups, bad))

    @staticmethod
    def _replica_groups(hlo: str):
        """Parse every replica_groups attribute in an HLO text — both the
        literal ``{{0,1},{2,3}}`` form and the iota form
        ``[G,S]<=[N...]`` (reshape of arange over the partition ids);
        iota forms with a transpose are skipped rather than guessed."""
        import re
        out = []
        for m in re.finditer(r"replica_groups=\{(\{[^}]*\}"
                             r"(?:,\{[^}]*\})*)\}", hlo):
            groups = []
            for g in re.findall(r"\{([^}]*)\}", m.group(1)):
                groups.append([int(x) for x in g.split(",") if x.strip()])
            out.append(groups)
        for m in re.finditer(r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\]",
                             hlo):
            g, s = int(m.group(1)), int(m.group(2))
            dims = [int(x) for x in m.group(3).split(",")]
            total = 1
            for d in dims:
                total *= d
            if total != g * s or len(dims) != 1:
                continue    # transposed iota: don't guess
            ids = list(range(total))
            out.append([ids[i * s:(i + 1) * s] for i in range(g)])
        return out

    def _walk(self, program, jaxpr, declared, counter) -> Iterable[Finding]:
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            path = counter.path_for(eqn)

            scope_axes = dict(declared)
            if prim == "shard_map":
                sm_axes = _mesh_axes_of(eqn)
                if sm_axes:
                    for ax, size in sm_axes.items():
                        if declared and ax not in declared:
                            yield self.finding(
                                program, path,
                                "shard_map runs over axis %r which the "
                                "program's declared mesh (%s) does not "
                                "carry — trace and deployment topology "
                                "disagree"
                                % (ax, ", ".join(sorted(declared))))
                        elif declared and declared[ax] != size:
                            yield self.finding(
                                program, path,
                                "shard_map mesh axis %r has size %d but "
                                "the program declares %d — the traced "
                                "program and the declared mesh disagree"
                                % (ax, size, declared[ax]))
                    # inside the shard_map body, ITS mesh is the law
                    scope_axes = dict(sm_axes)

            if prim in COLLECTIVE_PRIMS:
                axes = _named_axes(eqn)
                for ax in axes:
                    if scope_axes and ax not in scope_axes:
                        yield self.finding(
                            program, path,
                            "collective %s over axis %r, which the "
                            "program's mesh (%s) does not declare — "
                            "guaranteed unbound-axis failure on a real "
                            "fleet" % (prim, ax,
                                       ", ".join(sorted(scope_axes))))
                if prim == "ppermute":
                    perm = eqn.params.get("perm") or ()
                    sizes = [scope_axes[a] for a in axes
                             if a in scope_axes]
                    if sizes:
                        size = sizes[0]
                        bad = [(s, d) for s, d in perm
                               if not (0 <= s < size and 0 <= d < size)]
                        if bad:
                            yield self.finding(
                                program, path,
                                "ppermute pair%s %s outside axis size %d"
                                % ("s" if len(bad) > 1 else "",
                                   bad, size))

            if prim == "cond":
                branches = eqn.params.get("branches") or ()
                sigs = []
                for br in branches:
                    inner = getattr(br, "jaxpr", br)
                    sigs.append(_collective_signature(inner))
                if len(set(sigs)) > 1:
                    desc = "; ".join(
                        "branch %d: %s" % (i, list(s) if s else "none")
                        for i, s in enumerate(sigs))
                    yield self.finding(
                        program, path,
                        "cond branches issue different collective "
                        "sequences (%s) — deadlock if the predicate ever "
                        "diverges across devices" % desc)

            for _tag, sub in subjaxprs(eqn):
                yield from self._walk(program, sub, scope_axes, counter)
