"""TPU503 — collective-order and axis safety inside traced programs.

The deadlock class the AST tier's TPU301 cannot prove: collectives on TPU
are *rendezvous* ops — every participant of an axis must issue the same
collective sequence.  A ``lax.cond`` whose branches issue different
collective sequences deadlocks the fleet the first time the predicate
diverges across devices (and XLA will not stop you).  Likewise a
collective over an axis the program's mesh never declared, or a
``ppermute`` whose permutation indexes outside the axis extent, is a
guaranteed runtime failure that only shows up once a real multi-chip job
is already running.

Three mechanical checks over the jaxpr (recursing through pjit /
shard_map / scan / while bodies):

* **branch parity** — every ``cond`` has the identical ordered collective
  signature ``(primitive, axes)`` on all branches;
* **axis membership** — every named axis used by a collective is declared
  by the program's mesh (registry metadata or the enclosing ``shard_map``
  equation's mesh param), and any ``shard_map`` mesh agrees with the
  declared axis sizes;
* **permutation bounds** — ``ppermute`` pairs stay inside the axis size.

Scoping: collectives inside ``while`` bodies are checked for axis
membership but not trip-count uniformity (data-dependent trip counts are
undecidable statically); positional (int) axes are hardware-anonymous and
skipped.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..core import Finding
from .core import OpPathCounter, TracePass, TraceProgram, subjaxprs

__all__ = ["COLLECTIVE_PRIMS", "CollectiveOrderPass"]

#: rendezvous collectives (axis_index is per-device arithmetic, not a
#: rendezvous — excluded on purpose).
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter", "pgather",
})


def _named_axes(eqn) -> Tuple[str, ...]:
    """String axis names a collective equation rendezvouses over."""
    params = eqn.params
    raw = params.get("axes", params.get("axis_name", ()))
    if not isinstance(raw, (tuple, list)):
        raw = (raw,)
    return tuple(a for a in raw if isinstance(a, str))


def _collective_signature(jaxpr) -> Tuple[Tuple[str, Tuple[str, ...]], ...]:
    """Ordered (primitive, axes) sequence of every collective reachable in
    a jaxpr, depth-first — the rendezvous schedule a device executes."""
    sig: List[Tuple[str, Tuple[str, ...]]] = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            sig.append((eqn.primitive.name, _named_axes(eqn)))
        for _tag, sub in subjaxprs(eqn):
            sig.extend(_collective_signature(sub))
    return tuple(sig)


def _mesh_axes_of(eqn) -> Optional[Dict[str, int]]:
    mesh = eqn.params.get("mesh")
    if mesh is None:
        return None
    try:
        return dict(zip(mesh.axis_names, mesh.devices.shape))
    except Exception:
        try:
            return dict(mesh.shape)
        except Exception:
            return None


class CollectiveOrderPass(TracePass):
    """TPU503: uniform collective schedules, declared axes, legal perms."""

    rule = "TPU503"
    name = "collective_order"
    description = ("identical collective sequence on all cond branches; "
                   "collective axes declared by the mesh with consistent "
                   "sizes; ppermute permutations in range")

    def check(self, program: TraceProgram) -> Iterable[Finding]:
        if program.jaxpr is None:
            return
        declared = dict(program.meta.get("mesh_axes", {}) or {})
        jaxpr = getattr(program.jaxpr, "jaxpr", program.jaxpr)
        yield from self._walk(program, jaxpr, declared, OpPathCounter())

    def _walk(self, program, jaxpr, declared, counter) -> Iterable[Finding]:
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            path = counter.path_for(eqn)

            scope_axes = dict(declared)
            if prim == "shard_map":
                sm_axes = _mesh_axes_of(eqn)
                if sm_axes:
                    for ax, size in sm_axes.items():
                        if declared and ax not in declared:
                            yield self.finding(
                                program, path,
                                "shard_map runs over axis %r which the "
                                "program's declared mesh (%s) does not "
                                "carry — trace and deployment topology "
                                "disagree"
                                % (ax, ", ".join(sorted(declared))))
                        elif declared and declared[ax] != size:
                            yield self.finding(
                                program, path,
                                "shard_map mesh axis %r has size %d but "
                                "the program declares %d — the traced "
                                "program and the declared mesh disagree"
                                % (ax, size, declared[ax]))
                    # inside the shard_map body, ITS mesh is the law
                    scope_axes = dict(sm_axes)

            if prim in COLLECTIVE_PRIMS:
                axes = _named_axes(eqn)
                for ax in axes:
                    if scope_axes and ax not in scope_axes:
                        yield self.finding(
                            program, path,
                            "collective %s over axis %r, which the "
                            "program's mesh (%s) does not declare — "
                            "guaranteed unbound-axis failure on a real "
                            "fleet" % (prim, ax,
                                       ", ".join(sorted(scope_axes))))
                if prim == "ppermute":
                    perm = eqn.params.get("perm") or ()
                    sizes = [scope_axes[a] for a in axes
                             if a in scope_axes]
                    if sizes:
                        size = sizes[0]
                        bad = [(s, d) for s, d in perm
                               if not (0 <= s < size and 0 <= d < size)]
                        if bad:
                            yield self.finding(
                                program, path,
                                "ppermute pair%s %s outside axis size %d"
                                % ("s" if len(bad) > 1 else "",
                                   bad, size))

            if prim == "cond":
                branches = eqn.params.get("branches") or ()
                sigs = []
                for br in branches:
                    inner = getattr(br, "jaxpr", br)
                    sigs.append(_collective_signature(inner))
                if len(set(sigs)) > 1:
                    desc = "; ".join(
                        "branch %d: %s" % (i, list(s) if s else "none")
                        for i, s in enumerate(sigs))
                    yield self.finding(
                        program, path,
                        "cond branches issue different collective "
                        "sequences (%s) — deadlock if the predicate ever "
                        "diverges across devices" % desc)

            for _tag, sub in subjaxprs(eqn):
                yield from self._walk(program, sub, scope_axes, counter)
