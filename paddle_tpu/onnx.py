"""paddle.onnx — model export namespace (reference:
python/paddle/onnx/export.py, which delegates to the external paddle2onnx
package).

The TPU build's portable serving artifact is the jax.export/StableHLO module
written by ``paddle.static.save_inference_model`` / ``paddle.jit.save`` —
StableHLO is the interchange format of the XLA ecosystem the way ONNX is for
the CUDA runtimes.  ONNX serialization itself needs the onnx package, which
is not bundled; ``export`` raises with that guidance unless onnx is
importable.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise NotImplementedError(
            "paddle.onnx.export requires the 'onnx' package, which is not "
            "bundled in the TPU build.  Use paddle.jit.save / "
            "paddle.static.save_inference_model instead: they produce a "
            "standalone StableHLO artifact (the XLA-native equivalent) "
            "loadable with paddle.jit.load in any process.")
    raise NotImplementedError(
        "ONNX graph emission from jaxpr is not implemented; export via "
        "jit.save (StableHLO) and convert externally if ONNX is required.")
