"""Weight initializers (reference surface: python/paddle/nn/initializer/).

Each initializer is a callable ``(shape, dtype) -> jax array`` drawing from
the global PRNG stream; also usable as the ``default_initializer`` of
``Layer.create_parameter``.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import random as _rnd
from ...core import dtype as _dt


def _fan(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle convention OIHW for Conv2D weight (out, in, kh, kw)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None):
        return jnp.full(shape, self.value,
                        _dt.convert_dtype(dtype) or _dt.get_default_dtype())


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
        return (jax.random.normal(_rnd.next_key(), shape, dtype) * self.std
                + self.mean)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
        return (jax.random.truncated_normal(_rnd.next_key(), -2.0, 2.0, shape,
                                            dtype) * self.std + self.mean)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
        return jax.random.uniform(_rnd.next_key(), shape, dtype,
                                  minval=self.low, maxval=self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
        fi, fo = _fan(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(_rnd.next_key(), shape, dtype) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
        fi, fo = _fan(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(_rnd.next_key(), shape, dtype,
                                  minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None):
        dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
        fi, _ = _fan(shape)
        fi = self.fan_in or fi
        gain = (math.sqrt(2.0 / (1 + self.negative_slope ** 2))
                if self.nonlinearity in ("relu", "leaky_relu") else 1.0)
        std = gain / math.sqrt(fi)
        return jax.random.normal(_rnd.next_key(), shape, dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None):
        dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
        fi, _ = _fan(shape)
        fi = self.fan_in or fi
        gain = (math.sqrt(2.0 / (1 + self.negative_slope ** 2))
                if self.nonlinearity in ("relu", "leaky_relu") else 1.0)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(_rnd.next_key(), shape, dtype,
                                  minval=-limit, maxval=limit)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
        return jax.nn.initializers.orthogonal(scale=self.gain)(
            _rnd.next_key(), shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None):
        from ...core.tensor import Tensor
        v = self.value._array if isinstance(self.value, Tensor) else np.asarray(self.value)
        dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
        return jnp.asarray(v, dtype).reshape(shape)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        dtype = _dt.convert_dtype(dtype) or _dt.get_default_dtype()
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic * self.groups)):
            idx = (i, i % ic) + tuple(centers)
            out[idx] = 1.0
        return jnp.asarray(out, dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0
