"""Loss functionals (reference: python/paddle/nn/functional/loss.py).

cross_entropy follows the reference's fused softmax+CE semantics
(paddle/phi/kernels/gpu/cross_entropy_kernel.cu): computed from logits with a
numerically stable log-softmax, supporting soft labels, ignore_index and
class weights.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import call, wrap_op


def _pallas_ce_gate(flag_name, logits):
    """Shared eligibility gate for the Pallas CE/LSE routes: flag on, TPU
    backend, SINGLE device (a Mosaic custom call has no GSPMD partitioning
    rule — under a multi-device pjit XLA would all-gather the (N, V)
    logits per device; the sharded-model CE is ParallelCrossEntropy and
    the 'sep' routing, not this).  Returns (n, v, lead) or None."""
    from ...utils.flags import fast_get
    if not fast_get(flag_name):
        return None
    try:
        if jax.default_backend() != "tpu" or len(jax.devices()) != 1:
            return None
    except Exception:
        return None
    v = logits.shape[-1]
    lead = logits.shape[:-1]
    n = 1
    for dim in lead:
        n *= dim
    return n, v, lead


def _fused_ce_or_none(logits, lbl, ignore_index):
    """Opt-in route (FLAGS_use_pallas_ce=1) to the Pallas fused softmax-CE
    kernel.  Default stays XLA: the streaming-reduction path measured
    FASTER on the 345M bench (49.7k vs 49.1k tokens/s) — the VMEM budget
    caps the kernel at 8-row tiles whose grid overhead outweighs the fused
    gather.  The kernel remains the escape hatch for shapes where XLA's
    reduction fusion misbehaves.  Returns None to take the XLA path."""
    gate = _pallas_ce_gate("use_pallas_ce", logits)
    if gate is None:
        return None
    n, v, lead = gate
    from ...kernels import ce_pallas
    if not ce_pallas.supported(n, v):
        return None
    # explicit i32 index math, no x64 flip at this level (flipping x64
    # inside an outer trace miscompiles on newer jax — see the XLA gather
    # below); softmax_ce_pallas scopes its own kernel lowering internally
    idx = jnp.clip(lbl.astype(jnp.int32), 0, v - 1).reshape(n, 1)
    nll = ce_pallas.softmax_ce_pallas(logits.reshape(n, v), idx)
    nll = nll.reshape(lead)
    mask = (lbl != ignore_index)
    return jnp.where(mask, nll, 0.0)


def _streamed_lse_or_none(logits, axis):
    """One-pass streamed Pallas logsumexp over the class axis
    (FLAGS_use_pallas_lse): ONE read of the bf16 logits with online
    (max, sum-exp2) statistics vs XLA's two streaming reductions.
    Returns None to take the XLA path (non-TPU, multi-device, unsupported
    shape/dtype, or the class axis is not last)."""
    if axis not in (-1, logits.ndim - 1):
        return None
    if logits.dtype not in (jnp.bfloat16, jnp.float16, jnp.float32):
        return None
    gate = _pallas_ce_gate("use_pallas_lse", logits)
    if gate is None:
        return None
    n, v, lead = gate
    from ...kernels import ce_pallas
    if not ce_pallas.lse_supported(n, v, logits.dtype.itemsize):
        return None
    return ce_pallas.logsumexp_pallas(logits.reshape(n, v)).reshape(lead)


def _reduce(out, reduction, weight_sum=None):
    if reduction == "mean":
        if weight_sum is not None:
            return jnp.sum(out) / jnp.maximum(weight_sum, 1e-12)
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def softmax_with_cross_entropy_raw(logits, label, soft_label=False,
                                   ignore_index=-100, axis=-1):
    # f32 softmax statistics regardless of logits dtype (bf16 logits over a
    # 50k vocab lose the tail mass); XLA fuses the convert into the reduce
    if soft_label:
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        return -jnp.sum(label * logp, axis=axis)
    # hard labels: nll = logsumexp(logits) - logits[label].  Two streaming
    # reductions over the bf16 logits instead of materialising the full
    # (..., V) f32 log_softmax (for a GPT vocab that array is GBs of HBM
    # traffic; measured ~4ms/step off the 345M bench)
    lbl = label
    if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
        lbl = jnp.squeeze(lbl, axis)
    if axis in (-1, logits.ndim - 1):
        out = _fused_ce_or_none(logits, lbl, ignore_index)
        if out is not None:
            return out
    lse = _streamed_lse_or_none(logits, axis)
    if lse is None:
        # keep every elementwise use of `logits` in its own consumer fusion:
        # binding `lf = logits.astype(f32)` once made XLA CSE the convert and
        # MATERIALISE the full f32 logits (1.65 GB at GPT-2 bench shapes,
        # ~10 ms/step of HBM traffic); with per-consumer converts the bf16
        # matmul output is the only materialised array and each streaming
        # reduction fuses its own upcast
        # (a max-free clamped variant was benched and measured no faster —
        # XLA's two streaming reductions are not the bottleneck they look
        # like)
        m = jax.lax.stop_gradient(jnp.max(logits, axis=axis))
        mf = m.astype(jnp.float32)
        lse = mf + jnp.log(jnp.sum(
            jnp.exp(logits.astype(jnp.float32) - jnp.expand_dims(mf, axis)),
            axis=axis))
    # cast BEFORE the clip so every index op is i32: s64 labels would
    # otherwise put emulated 64-bit clamp/compare ops into the TPU program
    # (caught by tests/test_x64_audit.py; an earlier revision toggled
    # x64_scope(False) here, but flipping x64 inside an outer trace
    # miscompiles on newer jax — explicit casts are trace-stable)
    idx = jnp.clip(lbl.astype(jnp.int32), 0, logits.shape[axis] - 1)
    # promise_in_bounds is honest (idx just got clipped) and keeps the
    # gather + its transpose in i32; other modes convert through s64
    t = jnp.take_along_axis(logits, jnp.expand_dims(idx, axis), axis=axis,
                            mode="promise_in_bounds").astype(jnp.float32)
    nll = lse - jnp.squeeze(t, axis)
    mask = (lbl != ignore_index)
    return jnp.where(mask, nll, 0.0)


@wrap_op
def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    logits = input
    nclass = logits.shape[axis]
    if label_smoothing > 0.0:
        if not soft_label:
            onehot = jax.nn.one_hot(
                label if label.ndim < logits.ndim else jnp.squeeze(label, axis),
                nclass, dtype=logits.dtype, axis=axis)
            label = onehot
            soft_label = True
        label = label * (1 - label_smoothing) + label_smoothing / nclass
    if not use_softmax:
        # input is already a probability distribution
        logp = jnp.log(jnp.maximum(input, 1e-30))
        if soft_label:
            out = -jnp.sum(label * logp, axis=axis)
            return _reduce(out, reduction)
        lbl = label if label.ndim < input.ndim else jnp.squeeze(label, axis)
        out = -jnp.take_along_axis(logp, jnp.expand_dims(lbl, axis), axis=axis)
        out = jnp.squeeze(out, axis)
        return _reduce(out, reduction)
    out = softmax_with_cross_entropy_raw(logits, label, soft_label,
                                         ignore_index, axis)
    if weight is not None and not soft_label:
        lbl = label if label.ndim < logits.ndim else jnp.squeeze(label, axis)
        w = jnp.take(weight, jnp.clip(lbl, 0, nclass - 1))
        w = jnp.where(lbl != ignore_index, w, 0.0)
        out = out * w
        return _reduce(out, reduction, weight_sum=jnp.sum(w))
    if reduction == "mean" and not soft_label:
        lbl = label if label.ndim < logits.ndim else jnp.squeeze(label, axis)
        valid = (lbl != ignore_index).astype(out.dtype)
        return jnp.sum(out) / jnp.maximum(jnp.sum(valid), 1.0)
    return _reduce(out, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    def raw(lg, lb):
        loss = softmax_with_cross_entropy_raw(lg, lb, soft_label, ignore_index, axis)
        loss = jnp.expand_dims(loss, axis)
        if return_softmax:
            return loss, jax.nn.softmax(lg, axis=axis)
        return loss
    return call(raw, logits, label, name="softmax_with_cross_entropy")


@wrap_op
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    nll = -jnp.take_along_axis(input, jnp.expand_dims(label, 1), axis=1)
    nll = jnp.squeeze(nll, 1)
    mask = label != ignore_index
    if weight is not None:
        w = jnp.take(weight, jnp.clip(label, 0, input.shape[1] - 1))
        w = jnp.where(mask, w, 0.0)
        nll = nll * w
        if reduction == "mean":
            return jnp.sum(nll) / jnp.maximum(jnp.sum(w), 1e-12)
    nll = jnp.where(mask, nll, 0.0)
    if reduction == "mean":
        return jnp.sum(nll) / jnp.maximum(jnp.sum(mask.astype(nll.dtype)), 1.0)
    return _reduce(nll, reduction)


@wrap_op
def mse_loss(input, label, reduction="mean"):
    return _reduce(jnp.square(input - label), reduction)


@wrap_op
def l1_loss(input, label, reduction="mean"):
    return _reduce(jnp.abs(input - label), reduction)


@wrap_op
def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    d = jnp.abs(input - label)
    out = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    # paddle multiplies by delta
    out = out * delta
    return _reduce(out, reduction)


@wrap_op
def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    eps = 1e-12
    out = -(label * jnp.log(jnp.maximum(input, eps))
            + (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
    if weight is not None:
        out = out * weight
    return _reduce(out, reduction)


@wrap_op
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    max_val = jnp.maximum(-logit, 0.0)
    if pos_weight is not None:
        log_w = (pos_weight - 1.0) * label + 1.0
        out = (1 - label) * logit + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        out = (1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val
    if weight is not None:
        out = out * weight
    return _reduce(out, reduction)


@wrap_op
def kl_div(input, label, reduction="mean"):
    out = label * (jnp.log(jnp.maximum(label, 1e-12)) - input)
    if reduction == "batchmean":
        return jnp.sum(out) / input.shape[0]
    return _reduce(out, reduction)


@wrap_op
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    out = jnp.maximum(-label * (input - other) + margin, 0.0)
    return _reduce(out, reduction)


@wrap_op
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    out = jnp.where(label == 1.0, input, jnp.maximum(margin - input, 0.0))
    return _reduce(out, reduction)


@wrap_op
def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    cos = (jnp.sum(input1 * input2, axis=-1)
           / jnp.maximum(jnp.linalg.norm(input1, axis=-1)
                         * jnp.linalg.norm(input2, axis=-1), 1e-12))
    out = jnp.where(label == 1, 1.0 - cos, jnp.maximum(cos - margin, 0.0))
    return _reduce(out, reduction)


@wrap_op
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p),
                                 axis=-1), 1.0 / p)
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn = jnp.minimum(dn, dist(positive, negative))
    out = jnp.maximum(dp - dn + margin, 0.0)
    return _reduce(out, reduction)


@wrap_op
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = (1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) \
        + jnp.maximum(-logit, 0.0)
    p_t = p * label + (1 - p) * (1 - label)
    loss = ce * ((1 - p_t) ** gamma)
    if alpha >= 0:
        alpha_t = alpha * label + (1 - alpha) * (1 - label)
        loss = alpha_t * loss
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


@wrap_op
def log_loss(input, label, epsilon=1e-4):
    return -(label * jnp.log(input + epsilon)
             + (1 - label) * jnp.log(1 - input + epsilon))


@wrap_op
def square_error_cost(input, label):
    return jnp.square(input - label)


@wrap_op
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    # forward algorithm CTC in log space, vectorised over batch via vmap
    # log_probs: (T, B, C) paddle layout
    if log_probs.ndim == 3 and log_probs.shape[0] != labels.shape[0]:
        lp = jnp.transpose(log_probs, (1, 0, 2))  # (B, T, C)
    else:
        lp = log_probs
    lp = jax.nn.log_softmax(lp, axis=-1)
    B, T, C = lp.shape
    S = labels.shape[1]

    def single(lp_b, lab_b, t_len, l_len):
        ext = jnp.full((2 * S + 1,), blank, dtype=lab_b.dtype)
        ext = ext.at[1::2].set(lab_b)
        L = 2 * l_len + 1
        neg_inf = -1e30
        alpha = jnp.full((2 * S + 1,), neg_inf, jnp.float32)
        alpha = alpha.at[0].set(lp_b[0, blank])
        alpha = alpha.at[1].set(jnp.where(l_len > 0, lp_b[0, ext[1]], neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.array([True, True]), ext[2:] == ext[:-2]])

        def step(alpha, lp_t):
            a_prev = jnp.concatenate(
                [jnp.array([neg_inf], jnp.float32), alpha[:-1]])
            a_prev2 = jnp.concatenate(
                [jnp.array([neg_inf, neg_inf], jnp.float32), alpha[:-2]])
            a_prev2 = jnp.where(same_as_prev2, neg_inf, a_prev2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_prev), a_prev2)
            new_alpha = merged + lp_t[ext]
            return new_alpha, None

        def body(t, alpha):
            new_alpha, _ = step(alpha, lp_b[t])
            return jnp.where(t < t_len, new_alpha, alpha)

        alpha = jax.lax.fori_loop(1, T, body, alpha)
        final = jnp.logaddexp(alpha[2 * l_len], alpha[2 * l_len - 1])
        return -final

    losses = jax.vmap(single)(lp, labels, input_lengths, label_lengths)
    if reduction == "mean":
        return jnp.mean(losses / jnp.maximum(label_lengths, 1).astype(losses.dtype))
    return _reduce(losses, reduction)
