"""Normalization functionals (reference: python/paddle/nn/functional/norm.py).

layer_norm runs on the XLA-fused path by default (measured at peak on TPU —
PERF.md); FLAGS_use_pallas_norm=1 opts into the hand kernel in
kernels/norm_pallas.py.  batch_norm keeps running stats on the layer like
the reference (paddle/phi/kernels/gpu/batch_norm_kernel.cu semantics).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import call, wrap_op
from ...core.tensor import Tensor

def _use_pallas_norm() -> bool:
    from ...utils.flags import fast_get
    return bool(fast_get("use_pallas_norm"))


def layer_norm_raw(x, weight, bias, normalized_shape, epsilon=1e-5):
    n_axes = len(normalized_shape) if isinstance(normalized_shape, (list, tuple)) else 1
    axes = tuple(range(x.ndim - n_axes, x.ndim))
    if _use_pallas_norm() and n_axes == 1 and weight is not None \
            and bias is not None and x.shape[-1] % 128 == 0:
        # hand-kernel path (FLAGS_use_pallas_norm=1): XLA's fused LN is
        # already at peak (PERF.md), so this is opt-in
        from ...kernels.norm_pallas import (DEFAULT_BLOCK_ROWS,
                                            layer_norm_pallas)
        rows = 1
        for s in x.shape[:-1]:
            rows *= s
        if rows % 8 == 0:
            interpret = jax.default_backend() != "tpu"
            return layer_norm_pallas(x, weight, bias, epsilon,
                                     DEFAULT_BLOCK_ROWS, interpret)
    # statistics in f32 regardless of activation dtype, output cast back to
    # the input dtype: keeps bf16 activations bf16 through the residual
    # stream (an f32-promoting LN silently turns every downstream matmul
    # into an f32 MXU op — measured 0.42x -> the dominant bench regression)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


@wrap_op
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    return layer_norm_raw(x, weight, bias, normalized_shape, epsilon)


def rms_norm_raw(x, weight, epsilon=1e-6):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    return out.astype(x.dtype)


@wrap_op
def rms_norm(x, weight=None, epsilon=1e-6):
    return rms_norm_raw(x, weight, epsilon)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None):
    """Batch norm with running-stat update on the provided mean/var tensors."""
    if use_global_stats is None:
        use_global_stats = not training
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1 if isinstance(x, Tensor) else 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)

    if use_global_stats:
        def raw(a, rm, rv, w, b):
            shape = [1] * a.ndim
            shape[ch_axis] = -1
            out = (a - rm.reshape(shape)) * jax.lax.rsqrt(rv.reshape(shape) + epsilon)
            if w is not None:
                out = out * w.reshape(shape)
            if b is not None:
                out = out + b.reshape(shape)
            return out
        return call(raw, x, running_mean.detach(), running_var.detach(),
                    weight, bias, name="batch_norm_infer")

    # training: compute batch stats; update running stats eagerly (or, under
    # trace, via the functional-state mechanism in jit.functional_call)
    def raw(a, w, b):
        mean = jnp.mean(a, axis=reduce_axes)
        var = jnp.var(a, axis=reduce_axes)
        shape = [1] * a.ndim
        shape[ch_axis] = -1
        out = (a - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out, mean, var

    out, batch_mean, batch_var = call(raw, x, weight, bias, name="batch_norm")
    # running-stat update (mirrors reference momentum semantics:
    # running = momentum*running + (1-momentum)*batch)
    if running_mean is not None:
        running_mean._array = (momentum * running_mean._array
                               + (1.0 - momentum) * batch_mean._array.astype(running_mean._array.dtype))
    if running_var is not None:
        n = 1
        for i in reduce_axes:
            n *= x.shape[i]
        unbiased = batch_var._array * (n / max(n - 1, 1))
        running_var._array = (momentum * running_var._array
                              + (1.0 - momentum) * unbiased.astype(running_var._array.dtype))
    return out


@wrap_op
def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    if data_format.startswith("NC"):
        n, c = x.shape[0], x.shape[1]
        spatial = x.shape[2:]
        g = x.reshape((n, num_groups, c // num_groups) + spatial)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        g = (g - mean) * jax.lax.rsqrt(var + epsilon)
        out = g.reshape(x.shape)
        shape = (1, c) + (1,) * len(spatial)
        if weight is not None:
            out = out * weight.reshape(shape)
        if bias is not None:
            out = out + bias.reshape(shape)
        return out
    raise NotImplementedError("group_norm NHWC")


@wrap_op
def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW"):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        shape = (1, -1) + (1,) * (x.ndim - 2)
        out = out * weight.reshape(shape)
    if bias is not None:
        shape = (1, -1) + (1,) * (x.ndim - 2)
        out = out + bias.reshape(shape)
    return out


@wrap_op
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    sq = jnp.square(x)
    half = size // 2
    pad_cfg = [(0, 0)] * x.ndim
    pad_cfg[ch_axis] = (half, size - half - 1)
    padded = jnp.pad(sq, pad_cfg)
    windows = sum(jnp.take(padded, jnp.arange(i, i + x.shape[ch_axis]),
                           axis=ch_axis) for i in range(size))
    denom = (k + alpha * windows / size) ** beta
    return x / denom


def spectral_norm(weight, n_power_iterations=1, eps=1e-12, dim=0):
    def raw(w):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((wm.shape[0],), w.dtype)
        v = jnp.ones((wm.shape[1],), w.dtype)
        for _ in range(max(n_power_iterations, 1)):
            v = wm.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = wm @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ wm @ v
        return w / sigma
    return call(raw, weight, name="spectral_norm")
