"""Activation functionals (reference: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import wrap_op

relu = wrap_op(jax.nn.relu, name="relu")
relu6 = wrap_op(jax.nn.relu6, name="relu6")
elu = wrap_op(lambda x, alpha=1.0: jax.nn.elu(x, alpha), name="elu")
selu = wrap_op(lambda x, scale=1.0507009873554805, alpha=1.6732632423543772:
               scale * jnp.where(x > 0, x, alpha * jnp.expm1(x)), name="selu")
celu = wrap_op(lambda x, alpha=1.0: jax.nn.celu(x, alpha), name="celu")
gelu = wrap_op(lambda x, approximate=False: jax.nn.gelu(x, approximate=approximate),
               name="gelu")
silu = wrap_op(jax.nn.silu, name="silu")
swish = silu
mish = wrap_op(lambda x: x * jnp.tanh(jax.nn.softplus(x)), name="mish")
sigmoid = wrap_op(jax.nn.sigmoid, name="sigmoid")
hardsigmoid = wrap_op(lambda x, slope=1.0 / 6, offset=0.5:
                      jnp.clip(slope * x + offset, 0.0, 1.0), name="hardsigmoid")
hardswish = wrap_op(lambda x: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0,
                    name="hardswish")
hardtanh = wrap_op(lambda x, min=-1.0, max=1.0: jnp.clip(x, min, max),
                   name="hardtanh")
hardshrink = wrap_op(lambda x, threshold=0.5:
                     jnp.where(jnp.abs(x) > threshold, x, 0.0), name="hardshrink")
softshrink = wrap_op(lambda x, threshold=0.5:
                     jnp.where(x > threshold, x - threshold,
                               jnp.where(x < -threshold, x + threshold, 0.0)),
                     name="softshrink")
tanhshrink = wrap_op(lambda x: x - jnp.tanh(x), name="tanhshrink")
leaky_relu = wrap_op(lambda x, negative_slope=0.01:
                     jax.nn.leaky_relu(x, negative_slope), name="leaky_relu")
log_sigmoid = wrap_op(jax.nn.log_sigmoid, name="log_sigmoid")
softplus = wrap_op(lambda x, beta=1.0, threshold=20.0:
                   jnp.where(beta * x > threshold, x,
                             jnp.log1p(jnp.exp(beta * x)) / beta),
                   name="softplus")
softsign = wrap_op(jax.nn.soft_sign, name="softsign")
tanh = wrap_op(jnp.tanh, name="tanh")
thresholded_relu = wrap_op(lambda x, threshold=1.0:
                           jnp.where(x > threshold, x, 0.0),
                           name="thresholded_relu")


@wrap_op
def softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.softmax(x, axis=axis)


@wrap_op
def log_softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.log_softmax(x, axis=axis)


@wrap_op
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    from ...core import random as _rnd
    g = jax.random.gumbel(_rnd.next_key(), x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        hard_y = jnp.zeros_like(y)
        hard_y = jnp.put_along_axis(hard_y, idx, 1.0, axis=axis, inplace=False)
        y = hard_y + y - jax.lax.stop_gradient(y)
    return y


@wrap_op
def prelu(x, weight, data_format="NCHW"):
    w = weight
    if w.ndim == 1 and w.shape[0] > 1 and x.ndim > 2:
        if data_format == "NCHW":
            w = w.reshape((1, -1) + (1,) * (x.ndim - 2))
        else:
            w = w.reshape((1,) * (x.ndim - 1) + (-1,))
    return jnp.where(x > 0, x, w * x)


@wrap_op
def maxout(x, groups, axis=1):
    shape = list(x.shape)
    c = shape[axis]
    shape[axis:axis + 1] = [c // groups, groups]
    return jnp.max(x.reshape(shape), axis=axis + 1)


@wrap_op
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@wrap_op
def rrelu(x, lower=1.0 / 8, upper=1.0 / 3, training=True):
    from ...core import random as _rnd
    if training:
        slope = jax.random.uniform(_rnd.next_key(), x.shape, x.dtype,
                                   minval=lower, maxval=upper)
    else:
        slope = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, slope * x)
