"""Convolutions (reference: python/paddle/nn/functional/conv.py).

All convs lower to one XLA ``conv_general_dilated`` — the TPU equivalent of
the reference's cuDNN path (paddle/phi/kernels/gpudnn/conv_kernel.cu).  The
public layout default is NCHW for API parity; XLA's layout assignment picks
the TPU-friendly internal layout, so no manual NHWC transposes are needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import wrap_op


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _padding(padding, n, stride, ksize, dilation):
    if isinstance(padding, str):
        if padding.upper() == "SAME":
            return "SAME"
        if padding.upper() == "VALID":
            return "VALID"
    if isinstance(padding, (list, tuple)):
        p = list(padding)
        if len(p) == n:
            return [(int(v), int(v)) for v in p]
        if len(p) == 2 * n:
            return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]
        if len(p) == 1:
            return [(int(p[0]), int(p[0]))] * n
    return [(int(padding), int(padding))] * n


def _dim_numbers(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, n,
             channel_last):
    # paddle weights are (out, in/groups, *k) regardless of data_format
    dn = _dim_numbers(n, channel_last)
    if channel_last:
        # convert OIHW-style weight to HWIO-style
        perm = tuple(range(2, 2 + n)) + (1, 0)
        weight = jnp.transpose(weight, perm)
    out = jax.lax.conv_general_dilated(
        x, weight,
        window_strides=stride,
        padding=padding,
        rhs_dilation=dilation,
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None)
    if bias is not None:
        if channel_last:
            out = out + bias.reshape((1,) * (n + 1) + (-1,))
        else:
            out = out + bias.reshape((1, -1) + (1,) * n)
    return out


@wrap_op
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    cl = data_format in ("NLC",)
    return _conv_nd(x, weight, bias, _tuple(stride, 1),
                    _padding(padding, 1, stride, weight.shape[-1:], dilation),
                    _tuple(dilation, 1), groups, 1, cl)


@wrap_op
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    cl = data_format == "NHWC"
    return _conv_nd(x, weight, bias, _tuple(stride, 2),
                    _padding(padding, 2, stride, weight.shape[-2:], dilation),
                    _tuple(dilation, 2), groups, 2, cl)


@wrap_op
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    cl = data_format == "NDHWC"
    return _conv_nd(x, weight, bias, _tuple(stride, 3),
                    _padding(padding, 3, stride, weight.shape[-3:], dilation),
                    _tuple(dilation, 3), groups, 3, cl)


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, n, channel_last):
    # paddle transpose-conv weight: (in, out/groups, *k)
    dn = _dim_numbers(n, channel_last)
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    opad = _tuple(output_padding, n)
    ksz = weight.shape[2:]
    pad = _padding(padding, n, stride, ksz, dilation)
    if isinstance(pad, str):
        pad_pairs = None
    else:
        pad_pairs = pad
    # gradient-of-conv formulation: lhs_dilation = stride
    if pad_pairs is None:
        trans_pad = pad
    else:
        trans_pad = [
            (d * (k - 1) - p[0], d * (k - 1) - p[1] + op)
            for k, p, d, op in zip(ksz, pad_pairs, dilation, opad)]
    # weight (in, out/groups, *k) -> flip spatial, to (out, in/groups, *k)
    w = jnp.flip(weight, axis=tuple(range(2, 2 + n)))
    if groups > 1:
        ci = w.shape[0]
        w = w.reshape((groups, ci // groups) + w.shape[1:])
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((w.shape[0] * w.shape[1], ci // groups) + w.shape[3:])
    else:
        w = jnp.swapaxes(w, 0, 1)
    if channel_last:
        perm = tuple(range(2, 2 + n)) + (1, 0)
        w = jnp.transpose(w, perm)
    out = jax.lax.conv_general_dilated(
        x, w,
        window_strides=(1,) * n,
        padding=trans_pad,
        lhs_dilation=stride,
        rhs_dilation=dilation,
        dimension_numbers=_dim_numbers(n, channel_last),
        feature_group_count=groups)
    if bias is not None:
        if channel_last:
            out = out + bias.reshape((1,) * (n + 1) + (-1,))
        else:
            out = out + bias.reshape((1, -1) + (1,) * n)
    return out


@wrap_op
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, data_format="NCL"):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 1, data_format == "NLC")


@wrap_op
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW", output_size=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 2, data_format == "NHWC")


@wrap_op
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW", output_size=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 3, data_format == "NDHWC")
