"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py).

All pools are XLA reduce_window calls (the TPU analogue of the reference's
cuDNN pooling descriptors, paddle/phi/kernels/gpudnn/pool_kernel.cu).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import wrap_op


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else v * n))
    return tuple(int(v) for _ in range(n))


def _pool_pad(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        p = list(padding)
        if len(p) == n:
            return [(int(v), int(v)) for v in p]
        if len(p) == 2 * n:
            return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]
    return [(int(padding), int(padding))] * n


def _reduce_window(x, init, op, ksize, stride, pad, n, channel_last):
    if channel_last:
        dims = (1,) + ksize + (1,)
        strides = (1,) + stride + (1,)
        pad_cfg = ([(0, 0)] + pad + [(0, 0)]) if isinstance(pad, list) else pad
    else:
        dims = (1, 1) + ksize
        strides = (1, 1) + stride
        pad_cfg = ([(0, 0), (0, 0)] + pad) if isinstance(pad, list) else pad
    if isinstance(pad_cfg, str):
        pad_cfg = jax.lax.padtype_to_pads(x.shape, dims, strides, pad_cfg)
    return jax.lax.reduce_window(x, init, op, dims, strides, pad_cfg)


@wrap_op
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW"):
    ks = _tuple(kernel_size, 2)
    st = _tuple(stride if stride is not None else kernel_size, 2)
    pad = _pool_pad(padding, 2)
    cl = data_format == "NHWC"
    neg_inf = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    out = _reduce_window(x, neg_inf, jax.lax.max, ks, st, pad, 2, cl)
    if return_mask:
        idx = _pool_argmax(x, ks, st, pad, cl)
        return out, idx
    return out


def _pool_argmax(x, ks, st, pad, channel_last):
    # argmax indices within each window, flattened over H*W (paddle semantics)
    assert not channel_last
    n, c, h, w = x.shape
    lin = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
    lin = jnp.broadcast_to(lin, x.shape)
    # select index of max via reduce_window over (value, index) pairs
    def reducer(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)
    init = (jnp.asarray(-jnp.inf, x.dtype), jnp.asarray(-1.0, jnp.float32))
    vals, idx = jax.lax.reduce_window(
        (x, lin), init, reducer,
        (1, 1) + ks, (1, 1) + st,
        [(0, 0), (0, 0)] + pad if isinstance(pad, list) else pad)
    return idx.astype(jnp.int64)


@wrap_op
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW"):
    ks = _tuple(kernel_size, 2)
    st = _tuple(stride if stride is not None else kernel_size, 2)
    pad = _pool_pad(padding, 2)
    cl = data_format == "NHWC"
    summed = _reduce_window(x, 0.0, jax.lax.add, ks, st, pad, 2, cl)
    if divisor_override:
        return summed / divisor_override
    if exclusive and pad not in ("VALID",):
        ones = jnp.ones_like(x)
        counts = _reduce_window(ones, 0.0, jax.lax.add, ks, st, pad, 2, cl)
        return summed / counts
    return summed / float(np.prod(ks))


@wrap_op
def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False):
    ks = _tuple(kernel_size, 1)
    st = _tuple(stride if stride is not None else kernel_size, 1)
    pad = _pool_pad(padding, 1)
    neg_inf = -jnp.inf
    out = jax.lax.reduce_window(x, neg_inf, jax.lax.max, (1, 1) + ks,
                                (1, 1) + st, [(0, 0), (0, 0)] + pad)
    return out


@wrap_op
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False):
    ks = _tuple(kernel_size, 1)
    st = _tuple(stride if stride is not None else kernel_size, 1)
    pad = _pool_pad(padding, 1)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, 1) + ks,
                                   (1, 1) + st, [(0, 0), (0, 0)] + pad)
    if exclusive:
        counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                       (1, 1) + ks, (1, 1) + st,
                                       [(0, 0), (0, 0)] + pad)
        return summed / counts
    return summed / float(ks[0])


@wrap_op
def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    ks = _tuple(kernel_size, 3)
    st = _tuple(stride if stride is not None else kernel_size, 3)
    pad = _pool_pad(padding, 3)
    return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 1) + ks,
                                 (1, 1) + st, [(0, 0), (0, 0)] + pad)


@wrap_op
def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCDHW"):
    ks = _tuple(kernel_size, 3)
    st = _tuple(stride if stride is not None else kernel_size, 3)
    pad = _pool_pad(padding, 3)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, 1) + ks,
                                   (1, 1) + st, [(0, 0), (0, 0)] + pad)
    if divisor_override:
        return summed / divisor_override
    if exclusive:
        counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                       (1, 1) + ks, (1, 1) + st,
                                       [(0, 0), (0, 0)] + pad)
        return summed / counts
    return summed / float(np.prod(ks))


def _adaptive_windows(in_size, out_size):
    # start/end per output bin, paddle/torch adaptive pooling semantics
    starts = (np.arange(out_size) * in_size) // out_size
    ends = -(-(np.arange(1, out_size + 1) * in_size) // out_size)
    return starts, ends


@wrap_op
def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    os = _tuple(output_size, 2)
    h, w = x.shape[-2:]
    if h % os[0] == 0 and w % os[1] == 0:
        # uniform windows — single reduce_window
        ks = (h // os[0], w // os[1])
        return jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, 1) + ks,
                                     (1, 1) + ks, "VALID") / float(np.prod(ks))
    hs, he = _adaptive_windows(h, os[0])
    ws, we = _adaptive_windows(w, os[1])
    rows = [jnp.mean(x[..., s:e, :], axis=-2, keepdims=True) for s, e in zip(hs, he)]
    xh = jnp.concatenate(rows, axis=-2)
    cols = [jnp.mean(xh[..., :, s:e], axis=-1, keepdims=True) for s, e in zip(ws, we)]
    return jnp.concatenate(cols, axis=-1)


@wrap_op
def adaptive_max_pool2d(x, output_size, return_mask=False, data_format="NCHW"):
    os = _tuple(output_size, 2)
    h, w = x.shape[-2:]
    if h % os[0] == 0 and w % os[1] == 0:
        ks = (h // os[0], w // os[1])
        return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 1) + ks,
                                     (1, 1) + ks, "VALID")
    hs, he = _adaptive_windows(h, os[0])
    ws, we = _adaptive_windows(w, os[1])
    rows = [jnp.max(x[..., s:e, :], axis=-2, keepdims=True) for s, e in zip(hs, he)]
    xh = jnp.concatenate(rows, axis=-2)
    cols = [jnp.max(xh[..., :, s:e], axis=-1, keepdims=True) for s, e in zip(ws, we)]
    return jnp.concatenate(cols, axis=-1)


@wrap_op
def adaptive_avg_pool1d(x, output_size):
    l = x.shape[-1]
    os = int(output_size)
    if l % os == 0:
        k = l // os
        return jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, 1, k),
                                     (1, 1, k), "VALID") / float(k)
    ss, es = _adaptive_windows(l, os)
    return jnp.concatenate([jnp.mean(x[..., s:e], axis=-1, keepdims=True)
                            for s, e in zip(ss, es)], axis=-1)


@wrap_op
def adaptive_max_pool1d(x, output_size, return_mask=False):
    l = x.shape[-1]
    os = int(output_size)
    ss, es = _adaptive_windows(l, os)
    return jnp.concatenate([jnp.max(x[..., s:e], axis=-1, keepdims=True)
                            for s, e in zip(ss, es)], axis=-1)


@wrap_op
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    os = _tuple(output_size, 3)
    d, h, w = x.shape[-3:]
    if d % os[0] == 0 and h % os[1] == 0 and w % os[2] == 0:
        ks = (d // os[0], h // os[1], w // os[2])
        return jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, 1) + ks,
                                     (1, 1) + ks, "VALID") / float(np.prod(ks))
    raise NotImplementedError("non-divisible adaptive_avg_pool3d")
