"""Common functionals: linear, dropout, pad, embedding, interpolate, ...
(reference: python/paddle/nn/functional/common.py, input.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import random as _rnd
from ...core.dispatch import call, wrap_op
from ...core.tensor import Tensor


@wrap_op
def linear(x, weight, bias=None):
    # paddle stores Linear weight as (in, out): y = x @ W + b
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = _rnd.next_key()

    def raw(a):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype))
        return jnp.where(keep, a, jnp.zeros((), a.dtype))

    return call(raw, x, name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True):
    if not training or p == 0.0:
        return x
    key = _rnd.next_key()

    def raw(a):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef

    return call(raw, x, name="alpha_dropout")


@wrap_op
def embedding(x, weight, padding_idx=None, sparse=False):
    # bracket indexing (not jnp.take): take's fill/clip modes route index
    # math through s64 under global x64 — in the forward gather and again
    # in the scatter-add transpose — putting emulated 64-bit ops into TPU
    # programs (tests/test_x64_audit.py); w[x] stays in the input's i32
    out = weight[x]
    if padding_idx is not None and padding_idx >= 0:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros((), out.dtype), out)
    return out


def one_hot(x, num_classes):
    return call(lambda a: jax.nn.one_hot(a, num_classes), x, name="one_hot")


@wrap_op
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    pad = list(int(p) for p in pad)
    nd = x.ndim
    if len(pad) == nd * 2:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle convention: pad applies to the last len(pad)//2 spatial dims,
        # ordered (left, right, top, bottom, front, back) for NCHW-family
        n_spatial = len(pad) // 2
        cfg = [(0, 0)] * nd
        if data_format.startswith("NC"):
            spatial_dims = list(range(nd - n_spatial, nd))
        else:
            spatial_dims = list(range(1, 1 + n_spatial))
        # reverse: pad is given innermost-dim-first
        for i, d in enumerate(reversed(spatial_dims)):
            cfg[d] = (pad[2 * i], pad[2 * i + 1])
    if mode == "constant":
        return jnp.pad(x, cfg, mode="constant", constant_values=value)
    if mode == "reflect":
        return jnp.pad(x, cfg, mode="reflect")
    if mode == "replicate":
        return jnp.pad(x, cfg, mode="edge")
    if mode == "circular":
        return jnp.pad(x, cfg, mode="wrap")
    raise ValueError(f"unknown pad mode {mode}")


@wrap_op
def normalize(x, p=2, axis=1, epsilon=1e-12):
    if p == 2:
        denom = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    else:
        denom = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(denom, epsilon)


@wrap_op
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(jnp.square(x1), axis=axis))
    n2 = jnp.sqrt(jnp.sum(jnp.square(x2), axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


@wrap_op
def label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


@wrap_op
def bilinear(x1, x2, weight, bias=None):
    # weight: (out, in1, in2)
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    def raw(a):
        nchw = data_format.upper().startswith("NC")
        if not nchw:
            # to NCHW-like
            perm = (0, a.ndim - 1) + tuple(range(1, a.ndim - 1))
            a = jnp.transpose(a, perm)
        spatial = a.shape[2:]
        if size is not None:
            out_spatial = tuple(int(s) for s in
                                (size if isinstance(size, (list, tuple)) else [size]))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * len(spatial)
            out_spatial = tuple(int(np.floor(s * f)) for s, f in zip(spatial, sf))
        method = {"nearest": "nearest", "bilinear": "bilinear",
                  "trilinear": "trilinear", "bicubic": "bicubic",
                  "linear": "linear", "area": "linear"}[mode]
        if mode == "nearest":
            # jax.image nearest matches paddle align_corners=False
            out = jax.image.resize(a, a.shape[:2] + out_spatial, method="nearest")
        elif align_corners:
            out = _resize_align_corners(a, out_spatial, method)
        else:
            out = jax.image.resize(a, a.shape[:2] + out_spatial, method=method)
        if not nchw:
            perm = (0,) + tuple(range(2, out.ndim)) + (1,)
            out = jnp.transpose(out, perm)
        return out

    return call(raw, x, name="interpolate")


def _resize_align_corners(a, out_spatial, method):
    # align_corners=True: sample at exact corner-aligned grid via map_coordinates
    spatial = a.shape[2:]
    coords = []
    for s_in, s_out in zip(spatial, out_spatial):
        if s_out == 1:
            c = jnp.zeros((1,), jnp.float32)
        else:
            c = jnp.linspace(0.0, s_in - 1.0, s_out, dtype=jnp.float32)
        coords.append(c)
    mesh = jnp.meshgrid(*coords, indexing="ij")
    order = 0 if method == "nearest" else 1

    def per_image(img):
        return jax.scipy.ndimage.map_coordinates(img, mesh, order=order)

    return jax.vmap(jax.vmap(per_image))(a)


upsample = interpolate


@wrap_op
def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(n, h * r, w * r, c // (r * r))


@wrap_op
def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
        return x.reshape(n, c * r * r, h // r, w // r)
    raise NotImplementedError


@wrap_op
def channel_shuffle(x, groups, data_format="NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, groups, c // groups, h, w)
        x = jnp.transpose(x, (0, 2, 1, 3, 4))
        return x.reshape(n, c, h, w)
    raise NotImplementedError


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    from ...ops.manipulation import unfold as _unfold
    return _unfold(x, kernel_sizes, strides, paddings, dilations)


@wrap_op
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    n, ckk, L = x.shape
    c = ckk // (ks[0] * ks[1])
    oh, ow = output_sizes
    lh = (oh + 2 * pd[0] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
    lw = (ow + 2 * pd[1] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
    cols = x.reshape(n, c, ks[0], ks[1], lh, lw)
    out = jnp.zeros((n, c, oh + 2 * pd[0], ow + 2 * pd[1]), x.dtype)
    for i in range(ks[0]):
        for j in range(ks[1]):
            hi = i * dl[0]
            wj = j * dl[1]
            out = out.at[:, :, hi:hi + lh * st[0]:st[0],
                         wj:wj + lw * st[1]:st[1]].add(cols[:, :, i, j])
    return out[:, :, pd[0]:pd[0] + oh, pd[1]:pd[1] + ow]


@wrap_op
def sequence_mask(x, maxlen=None, dtype="int64"):
    """reference: paddle.nn.functional.sequence_mask
    (operators/sequence_ops/sequence_mask_op.*): mask[i, ..., j] = j < x[i].
    ``maxlen=None`` uses max(x) — a data-dependent shape, so inside jit
    pass an explicit maxlen (static shapes under XLA)."""
    from ...core.dtype import convert_dtype
    if maxlen is None:
        maxlen = int(jnp.max(x))
    steps = jnp.arange(int(maxlen))
    mask = steps < jnp.expand_dims(x, -1)
    return mask.astype(convert_dtype(dtype))
