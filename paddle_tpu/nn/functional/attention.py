"""Attention functionals.

``scaled_dot_product_attention`` routes to the Pallas flash-attention kernel
on TPU (paddle_tpu.kernels.flash_attention) and to a reference XLA
implementation elsewhere — the TPU-native answer to the reference's fused
FMHA (paddle/fluid/operators/fused/fmha_ref.h, fused_attention_op).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import call
from ...core.tensor import Tensor


def sdpa_reference_raw(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False,
                       scale=None, dropout_key=None):
    """Plain-XLA attention. q/k/v: (B, S, H, D) paddle layout."""
    bthd = q.ndim == 4
    if bthd:
        q_ = jnp.swapaxes(q, 1, 2)  # (B, H, S, D)
        k_ = jnp.swapaxes(k, 1, 2)
        v_ = jnp.swapaxes(v, 1, 2)
    else:
        q_, k_, v_ = q, k, v
    d = q_.shape[-1]
    s = scale if scale is not None else 1.0 / jnp.sqrt(jnp.asarray(d, q_.dtype))
    logits = jnp.einsum("...qd,...kd->...qk", q_, k_) * s
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(causal, logits, jnp.asarray(-1e30, logits.dtype))
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, jnp.asarray(-1e30, logits.dtype))
        else:
            logits = logits + attn_mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q_.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("...qk,...kd->...qd", probs, v_)
    if bthd:
        out = jnp.swapaxes(out, 1, 2)
    return out


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None,
                                 use_flash=True, sequence_parallel="auto"):
    """q/k/v: (batch, seq, heads, head_dim) — reference layout
    (python/paddle incubate FusedMultiHeadAttention input layout).

    SEQUENCE PARALLELISM: inside a shard_map trace with the framework's
    sequence-parallel axis 'sep' bound, the CONTRACT is that q/k/v are the
    LOCAL contiguous token shards, and attention runs via the ppermute
    ring-KV rotation over the axis (SURVEY §5.7).  Shapes/configurations
    the ring path cannot express there (attn_mask, active dropout, cached
    decode with q_len != k_len) raise rather than silently attending
    shard-locally.  Pass ``sequence_parallel=False`` for code inside a
    'sep' shard_map that has already gathered the full sequence.  Plain
    pjit/GSPMD traces never bind 'sep' manually and are unaffected.
    """
    from ...core import random as _rnd
    dropout_key = _rnd.next_key() if (dropout_p > 0.0 and training) else None
    if not training:
        dropout_p = 0.0

    def raw(q, k, v, m):
        if sequence_parallel:
            from ...distributed.collective import axis_in_trace
            if axis_in_trace("sep"):
                if dropout_p > 0.0 or q.ndim != 4 \
                        or q.shape[1] != k.shape[1]:
                    raise NotImplementedError(
                        "scaled_dot_product_attention under the 'sep' "
                        "sequence-parallel axis supports only dropout-free "
                        "self-attention (the ring schedule); disable "
                        "attention dropout under sequence parallelism, or "
                        "pass sequence_parallel=False if the sequence was "
                        "already gathered")
                if q.shape[2] % k.shape[2]:
                    # curated error before ring_attention's einsum would
                    # die with an opaque shape mismatch (ADVICE r3);
                    # divisible head counts route as grouped-query (the
                    # ring rotates the GROUPED K/V — wire bytes shrink by
                    # the group factor, r4 Weak #4)
                    raise NotImplementedError(
                        "grouped-query attention under the 'sep' ring "
                        "needs q heads (%d) divisible by k/v heads (%d)"
                        % (q.shape[2], k.shape[2]))
                mask = None
                if m is not None:
                    # ring contract: ADDITIVE mask, local q rows x global
                    # key axis (each ring step slices its shard's columns)
                    if m.dtype == jnp.bool_:
                        raise NotImplementedError(
                            "boolean attn_mask under the 'sep' ring is "
                            "not supported — pass an additive float mask "
                            "of shape (..., S_local, S_global) (its rows "
                            "are this rank's local q positions)")
                    if m.shape[-2] != q.shape[1]:
                        raise ValueError(
                            "attn_mask rows (%d) must equal the LOCAL "
                            "sequence shard (%d) under the 'sep' ring; "
                            "columns span the GLOBAL key axis"
                            % (m.shape[-2], q.shape[1]))
                    mask = m
                from ...distributed.ring_attention import ring_attention
                out = ring_attention(
                    jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                    jnp.swapaxes(v, 1, 2), "sep", causal=is_causal,
                    scale=scale, attn_mask=mask)  # ring is (B, H, S, D)
                return jnp.swapaxes(out, 1, 2)
        if use_flash and m is None and dropout_p == 0.0:
            from ...kernels import flash_attention as fa
            if fa.supported(q, k):
                return fa.flash_attention_bshd(q, k, v, causal=is_causal,
                                               scale=scale)
        return sdpa_reference_raw(q, k, v, m, dropout_p, is_causal, scale,
                                  dropout_key)

    return call(raw, query, key, value, attn_mask, name="sdpa")
