"""paddle_tpu.nn.utils (reference surface: python/paddle/nn/utils/) —
parameter-surgery helpers: gradient clipping, flat-vector round-trips and
the weight/spectral reparameterizations.

Reparameterization on TPU: the reference mutates the layer's op graph
(``WeightNormParamAttr`` / a spectral-norm op before every matmul); here
the same effect is a ``forward_pre_hook`` that recomputes the effective
``weight`` from the decomposed parameters on every call — inside a jit
trace that is just more fused elementwise work, no graph surgery.
"""
from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from .clip import clip_grad_norm_  # noqa: F401  (reference home is here)

__all__ = ["clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters", "weight_norm", "remove_weight_norm",
           "spectral_norm"]


def clip_grad_value_(parameters, clip_value):
    """In-place elementwise clamp of parameters' ``.grad`` to
    [-clip_value, clip_value] (reference: nn/utils/clip_grad_value_)."""
    clip_value = float(clip_value)
    if clip_value < 0:
        raise ValueError("clip_value must be non-negative, got %r"
                         % clip_value)
    for p in parameters:
        if p.grad is not None:
            p.grad._array = jnp.clip(p.grad._array, -clip_value,
                                     clip_value)


def parameters_to_vector(parameters, name=None) -> Tensor:
    """Flatten parameters into one 1-D tensor (reference:
    nn/utils/transform_parameters.py)."""
    params = list(parameters)
    if not params:
        raise ValueError("parameters_to_vector got an empty list")
    return Tensor(jnp.concatenate(
        [p._array.reshape(-1) for p in params]))


def vector_to_parameters(vec, parameters, name=None):
    """Inverse of :func:`parameters_to_vector`: slice ``vec`` back into
    the parameters, in place."""
    arr = vec._array if isinstance(vec, Tensor) else jnp.asarray(vec)
    params = list(parameters)
    total = sum(int(p._array.size) for p in params)
    if int(arr.size) != total:
        raise ValueError(
            "vector has %d elements but the parameters hold %d"
            % (int(arr.size), total))
    off = 0
    for p in params:
        n = int(p._array.size)
        p._array = arr[off:off + n].reshape(p._array.shape) \
            .astype(p._array.dtype)
        off += n


def _norm_except_dim(w, dim):
    """L2 norm over all axes except ``dim`` (paddle/torch weight_norm
    convention); dim=None -> norm over everything (scalar g)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(w)))
    axes = tuple(a for a in range(w.ndim) if a != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=True))


def weight_norm(layer, name: str = "weight", dim: int = 0):
    """Reparameterize ``layer.<name>`` as direction*magnitude
    (w = g * v / ||v||, reference nn/utils/weight_norm_hook.py).

    ``<name>_g`` / ``<name>_v`` become the trainable parameters; the
    effective weight is recomputed by a forward_pre_hook on every call
    (so optimizer steps on g/v are reflected immediately, eager or
    traced).  ``dim=None`` uses one scalar magnitude."""
    if hasattr(layer, name + "_v"):
        raise ValueError("weight_norm already applied to %r" % name)
    w = getattr(layer, name)
    w_arr = w._array
    g = Parameter(_norm_except_dim(w_arr, dim))
    v = Parameter(w_arr)
    # the original entry must stop being a trainable Parameter: drop it
    # from _parameters and rebind as a plain attribute-computed buffer
    del layer._parameters[name]
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    object.__setattr__(layer, "_weight_norm_cfg_" + name, (dim,))

    def _recompute(lyr, _inputs):
        gg = getattr(lyr, name + "_g")._array
        vv = getattr(lyr, name + "_v")._array
        norm = _norm_except_dim(vv, dim)
        eff = vv * (gg / jnp.maximum(norm, 1e-12))
        object.__setattr__(lyr, name, Tensor(eff))
        return None

    h = layer.register_forward_pre_hook(_recompute)
    object.__setattr__(layer, "_weight_norm_hook_" + name, h)
    _recompute(layer, None)
    return layer


def remove_weight_norm(layer, name: str = "weight"):
    """Undo :func:`weight_norm`: bake the current effective weight back
    into a single Parameter and drop the hook + g/v."""
    helper = getattr(layer, "_weight_norm_hook_" + name, None)
    if helper is None:
        raise ValueError("weight_norm was not applied to %r" % name)
    helper.remove()
    eff = getattr(layer, name)
    for suffix in ("_g", "_v"):
        layer._parameters.pop(name + suffix, None)
        if name + suffix in layer.__dict__:
            del layer.__dict__[name + suffix]
    for attr in ("_weight_norm_hook_" + name, "_weight_norm_cfg_" + name,
                 name):
        # the hook's effective-weight Tensor lives in __dict__ and would
        # shadow the restored Parameter on attribute lookup
        if attr in layer.__dict__:
            del layer.__dict__[attr]
    layer.add_parameter(name, Parameter(eff._array))
    return layer


def spectral_norm(layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim: int = 0):
    """Normalize ``layer.<name>`` by its largest singular value, estimated
    with power iteration on every forward (reference:
    nn/utils/spectral_norm_hook.py; the layer twin is nn.SpectralNorm).

    Stateless TPU variant: the u/v power-iteration vectors are recomputed
    from a fixed start each call instead of carried as mutable buffers —
    trace-pure, so the hook works identically under jit."""
    from . import functional as F

    w = getattr(layer, name)
    del layer._parameters[name]
    layer.add_parameter(name + "_orig", Parameter(w._array))

    def _recompute(lyr, _inputs):
        worig = getattr(lyr, name + "_orig")
        eff = F.spectral_norm(worig, n_power_iterations, eps, dim)
        arr = eff._array if isinstance(eff, Tensor) else eff
        object.__setattr__(lyr, name, Tensor(arr))
        return None

    h = layer.register_forward_pre_hook(_recompute)
    object.__setattr__(layer, "_spectral_norm_hook_" + name, h)
    _recompute(layer, None)
    return layer
