"""RNN layers (reference: python/paddle/nn/layer/rnn.py).

TPU-native design: each layer-direction is ONE ``lax.scan`` over time —
compiler-friendly static control flow (the reference runs per-step cuDNN
kernels / a C++ while-op instead).  The whole multi-layer stack is a single
traced op, so grads flow through scan's native VJP.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ... import ops
from ...core.dispatch import call
from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer


def _lstm_step(carry, x_t, w_ih, w_hh, b_ih, b_hh):
    h, c = carry
    gates = x_t @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        gates = gates + b_ih + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c = f * c + i * g
    h = o * jnp.tanh(c)
    return (h, c), h


def _gru_step(carry, x_t, w_ih, w_hh, b_ih, b_hh):
    h = carry
    xr, xz, xn = jnp.split(x_t @ w_ih.T + (b_ih if b_ih is not None else 0.0), 3, axis=-1)
    hr, hz, hn = jnp.split(h @ w_hh.T + (b_hh if b_hh is not None else 0.0), 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    h = (1.0 - z) * n + z * h
    return h, h


def _rnn_step(carry, x_t, w_ih, w_hh, b_ih, b_hh, act):
    h = carry
    out = x_t @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        out = out + b_ih + b_hh
    h = jnp.tanh(out) if act == "tanh" else jax.nn.relu(out)
    return h, h


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        return ops.full([b, self.hidden_size], init_value,
                        dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter((hidden_size, input_size),
                                               default_initializer=u)
        self.weight_hh = self.create_parameter((hidden_size, hidden_size),
                                               default_initializer=u)
        self.bias_ih = (None if bias_ih_attr is False else
                        self.create_parameter((hidden_size,), is_bias=True,
                                              default_initializer=u))
        self.bias_hh = (None if bias_hh_attr is False else
                        self.create_parameter((hidden_size,), is_bias=True,
                                              default_initializer=u))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        def raw(x, h, wi, wh, bi, bh):
            new_h, _ = _rnn_step(h, x, wi, wh, bi, bh, self.activation)
            return new_h
        h = call(raw, inputs, states, self.weight_ih, self.weight_hh,
                 self.bias_ih, self.bias_hh, name="rnn_cell")
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter((4 * hidden_size, input_size),
                                               default_initializer=u)
        self.weight_hh = self.create_parameter((4 * hidden_size, hidden_size),
                                               default_initializer=u)
        self.bias_ih = (None if bias_ih_attr is False else
                        self.create_parameter((4 * hidden_size,), is_bias=True,
                                              default_initializer=u))
        self.bias_hh = (None if bias_hh_attr is False else
                        self.create_parameter((4 * hidden_size,), is_bias=True,
                                              default_initializer=u))

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
            states = (h, c)
        def raw(x, h, c, wi, wh, bi, bh):
            (nh, nc), _ = _lstm_step((h, c), x, wi, wh, bi, bh)
            return nh, nc
        h, c = call(raw, inputs, states[0], states[1], self.weight_ih,
                    self.weight_hh, self.bias_ih, self.bias_hh, name="lstm_cell")
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter((3 * hidden_size, input_size),
                                               default_initializer=u)
        self.weight_hh = self.create_parameter((3 * hidden_size, hidden_size),
                                               default_initializer=u)
        self.bias_ih = (None if bias_ih_attr is False else
                        self.create_parameter((3 * hidden_size,), is_bias=True,
                                              default_initializer=u))
        self.bias_hh = (None if bias_hh_attr is False else
                        self.create_parameter((3 * hidden_size,), is_bias=True,
                                              default_initializer=u))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        def raw(x, h, wi, wh, bi, bh):
            nh, _ = _gru_step(h, x, wi, wh, bi, bh)
            return nh
        h = call(raw, inputs, states, self.weight_ih, self.weight_hh,
                 self.bias_ih, self.bias_hh, name="gru_cell")
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Run a cell over time (reference: nn.RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        t_axis = 0 if self.time_major else 1
        steps = inputs.shape[t_axis]
        outputs = []
        states = initial_states
        rng = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for t in rng:
            x_t = ops.getitem(inputs, (slice(None), t) if t_axis == 1 else t)
            out, states = self.cell(x_t, states)
            outputs.append(out)
        if self.is_reverse:
            outputs = outputs[::-1]
        out = ops.stack(outputs, axis=t_axis)
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        out_fw, fw_states = self.rnn_fw(inputs, st_fw)
        out_bw, bw_states = self.rnn_bw(inputs, st_bw)
        out = ops.concat([out_fw, out_bw], axis=-1)
        return out, (fw_states, bw_states)


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) scan-based recurrent stack."""

    MODE = "LSTM"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, activation="tanh", name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.num_directions = 2 if direction in ("bidirect", "bidirectional") else 1
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN": 1}[self.MODE]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._weights = []
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = (input_size if layer == 0
                         else hidden_size * self.num_directions)
                suffix = f"l{layer}" + ("_reverse" if d == 1 else "")
                w_ih = self.create_parameter((gate_mult * hidden_size, in_sz),
                                             default_initializer=u)
                w_hh = self.create_parameter((gate_mult * hidden_size, hidden_size),
                                             default_initializer=u)
                b_ih = self.create_parameter((gate_mult * hidden_size,),
                                             is_bias=True, default_initializer=u)
                b_hh = self.create_parameter((gate_mult * hidden_size,),
                                             is_bias=True, default_initializer=u)
                self.add_parameter(f"weight_ih_{suffix}", w_ih)
                self.add_parameter(f"weight_hh_{suffix}", w_hh)
                self.add_parameter(f"bias_ih_{suffix}", b_ih)
                self.add_parameter(f"bias_hh_{suffix}", b_hh)
                self._weights.append((w_ih, w_hh, b_ih, b_hh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = self.MODE
        act = self.activation
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        time_major = self.time_major
        dropout = self.dropout if self.training else 0.0
        from ...core import random as _rnd
        drop_key = _rnd.next_key() if dropout > 0 else None

        def raw(x, h0, c0, *flat_w):
            # x: (B, T, I) if not time_major else (T, B, I)
            if time_major:
                x = jnp.swapaxes(x, 0, 1)
            B = x.shape[0]
            ws = [flat_w[i * 4:(i + 1) * 4] for i in range(nl * nd)]
            h_out, c_out = [], []
            layer_in = x
            for layer in range(nl):
                dir_outs = []
                for d in range(nd):
                    w_ih, w_hh, b_ih, b_hh = ws[layer * nd + d]
                    idx = layer * nd + d
                    h_init = h0[idx]
                    seq = jnp.swapaxes(layer_in, 0, 1)  # (T, B, I)
                    if d == 1:
                        seq = jnp.flip(seq, 0)
                    if mode == "LSTM":
                        c_init = c0[idx]
                        def step(carry, x_t, w_ih=w_ih, w_hh=w_hh, b_ih=b_ih, b_hh=b_hh):
                            return _lstm_step(carry, x_t, w_ih, w_hh, b_ih, b_hh)
                        (h_f, c_f), outs = jax.lax.scan(step, (h_init, c_init), seq)
                        c_out.append(c_f)
                    elif mode == "GRU":
                        def step(carry, x_t, w_ih=w_ih, w_hh=w_hh, b_ih=b_ih, b_hh=b_hh):
                            return _gru_step(carry, x_t, w_ih, w_hh, b_ih, b_hh)
                        h_f, outs = jax.lax.scan(step, h_init, seq)
                    else:
                        def step(carry, x_t, w_ih=w_ih, w_hh=w_hh, b_ih=b_ih, b_hh=b_hh):
                            return _rnn_step(carry, x_t, w_ih, w_hh, b_ih, b_hh, act)
                        h_f, outs = jax.lax.scan(step, h_init, seq)
                    h_out.append(h_f)
                    if d == 1:
                        outs = jnp.flip(outs, 0)
                    dir_outs.append(jnp.swapaxes(outs, 0, 1))  # (B, T, H)
                layer_in = (dir_outs[0] if nd == 1
                            else jnp.concatenate(dir_outs, axis=-1))
                if dropout > 0 and layer < nl - 1:
                    keep = jax.random.bernoulli(
                        jax.random.fold_in(drop_key, layer), 1.0 - dropout,
                        layer_in.shape)
                    layer_in = jnp.where(keep, layer_in / (1.0 - dropout), 0.0)
            out = layer_in
            if time_major:
                out = jnp.swapaxes(out, 0, 1)
            h_stack = jnp.stack(h_out, 0)
            if mode == "LSTM":
                return out, h_stack, jnp.stack(c_out, 0)
            return out, h_stack

        B = inputs.shape[1] if time_major else inputs.shape[0]
        if initial_states is None:
            zeros = ops.zeros([nl * nd, B, hs], inputs.dtype)
            if mode == "LSTM":
                initial_states = (zeros, ops.zeros([nl * nd, B, hs], inputs.dtype))
            else:
                initial_states = zeros
        flat_w = [w for tup in self._weights for w in tup]
        if mode == "LSTM":
            h0, c0 = initial_states
            out, h, c = call(raw, inputs, h0, c0, *flat_w, name=f"{mode}_stack")
            return out, (h, c)
        h0 = initial_states
        out, h = call(lambda x, h0_, *w: raw(x, h0_, None, *w), inputs, h0,
                      *flat_w, name=f"{mode}_stack")
        return out, h


class SimpleRNN(_RNNBase):
    MODE = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation, **kw)


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"
