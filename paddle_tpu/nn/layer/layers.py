"""nn.Layer — the module base class.

API parity target: python/paddle/fluid/dygraph/layers.py:83 (Layer), with
parameters(), named_parameters(), sublayers(), state_dict(), buffers,
forward/backward hooks, train/eval, apply, to().  TPU-native addition: every
Layer is also usable *functionally* — ``layer.functional_state()`` exports the
parameter pytree and ``paddle_tpu.jit.functional_call`` runs forward against
an externally supplied pytree, which is what the compiled/pjit training path
uses.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np

from ...core import dtype as _dt
from ...core.tensor import Parameter, Tensor


class HookRemoveHelper:
    next_id = 0

    def __init__(self, hooks):
        self._hooks = hooks
        self._id = HookRemoveHelper.next_id
        HookRemoveHelper.next_id += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = _dt.convert_dtype(dtype)
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- construction -------------------------------------------------------
    def create_parameter(self, shape, dtype=None, attr=None, is_bias=False,
                         default_initializer=None):
        from .. import initializer as I
        dtype = _dt.convert_dtype(dtype) or self._dtype
        init = None
        name = None
        if attr is not None and attr is not False:
            init = getattr(attr, "initializer", None)
            name = getattr(attr, "name", None)
        if init is None:
            init = default_initializer or (I.Constant(0.0) if is_bias
                                           else I.XavierNormal())
        arr = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(arr, dtype=dtype, name=name)
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        else:
            self._non_persistable_buffer_names.discard(name)
        return tensor

    # -- attribute routing --------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                params.pop(name)
            if layers is not None and name in layers and value is None:
                layers.pop(name)
            if buffers is not None and name in buffers:
                if isinstance(value, Tensor):
                    buffers[name] = value
                elif value is None:
                    buffers.pop(name)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    # -- traversal ----------------------------------------------------------
    def named_sublayers(self, prefix="", include_self=False, layers_set=None
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None or id(layer) in layers_set:
                continue
            layers_set.add(id(layer))
            p = prefix + ("." if prefix else "") + name
            yield p, layer
            yield from layer.named_sublayers(prefix=p, include_self=False,
                                             layers_set=layers_set)

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for layer_name, layer in self.named_sublayers(prefix=prefix,
                                                      include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (layer_name + ("." if layer_name else "") + pname, p)
            if not include_sublayers:
                break

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="") -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for layer_name, layer in self.named_sublayers(prefix=prefix,
                                                      include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (layer_name + ("." if layer_name else "") + bname, b)

    def buffers(self) -> List[Tensor]:
        return [b for _, b in self.named_buffers()]

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # -- modes --------------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        h = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[h._id] = hook
        return h

    def register_forward_post_hook(self, hook):
        h = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[h._id] = hook
        return h

    # -- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = collections.OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            dest[name] = p
        for lname, layer in self.named_sublayers(prefix=structured_name_prefix,
                                                 include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                dest[lname + ("." if lname else "") + bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                v = state_dict[name]
                arr = v._array if isinstance(v, Tensor) else np.asarray(v)
                t.set_value(Tensor(arr).astype(t.dtype))
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype / device movement -------------------------------------------
    def to(self, device=None, dtype=None, blocking=True):
        if dtype is not None:
            self._convert(dtype)
        return self

    def astype(self, dtype):
        self._convert(dtype)
        return self

    def _convert(self, dtype):
        dtype = _dt.convert_dtype(dtype)
        for p in self.parameters():
            if _dt.is_floating(p.dtype):
                p._array = p._array.astype(dtype)
        for b in self.buffers():
            if _dt.is_floating(b.dtype):
                b._array = b._array.astype(dtype)
        self._dtype = dtype

    def float(self):
        return self.astype("float32")

    def bfloat16(self):
        return self.astype("bfloat16")

    # -- functional bridge --------------------------------------------------
    def functional_state(self) -> Dict[str, object]:
        """Export {name: jax array} for params + persistable buffers — the
        pytree the compiled path feeds to functional_call."""
        return {name: t._array for name, t in self.state_dict().items()}

    def load_functional_state(self, tree: Dict[str, object]):
        sd = self.state_dict()
        for name, arr in tree.items():
            if name in sd:
                sd[name]._array = arr

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split("\n")
            rep = [rep[0]] + ["  " + r for r in rep[1:]]
            lines.append(f"  ({name}): " + "\n".join(rep))
        main = f"{self.__class__.__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        if idx < 0:
            idx += len(self)
        return self._sub_layers[str(idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, l in layers[0].items():
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, (list, tuple)) and len(l) == 2 and isinstance(l[0], str):
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def append(self, p):
        self.add_parameter(str(len(self)), p)
        return self
