"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum,
            epsilon=self.epsilon, data_format=self.data_format,
            use_global_stats=self.use_global_stats)

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.

    Under pjit/GSPMD the batch axis is sharded and XLA computes global batch
    statistics automatically when the reduction spans the data axis — so
    SyncBatchNorm is behaviourally BatchNorm here (reference needed a custom
    NCCL kernel: paddle/fluid/operators/sync_batch_norm_op.cu).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for _, sub in layer.named_sublayers(include_self=True):
            pass
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_channels,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            self.epsilon, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW"):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps

    def forward(self, weight):
        return F.spectral_norm(weight, self.power_iters, self.eps, self.dim)
