"""Gradient clipping utilities (reference: python/paddle/fluid/clip.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


def clip_grad_norm_(parameters, max_norm, norm_type=2.0):
    """In-place global-norm clip over parameters' ``.grad``."""
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.asarray(0.0, jnp.float32))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._array)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._array) ** norm_type) for g in grads]))
        total = total ** (1.0 / norm_type)
    clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for g in grads:
        g._array = g._array * clip_coef.astype(g._array.dtype)
    return Tensor(total)


def clip_grads_by_global_norm_tree(grads_tree_leaves, clip_norm):
    """Functional global-norm clip over a list of grad arrays (compiled path)."""
    total = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in grads_tree_leaves))
    coef = jnp.minimum(clip_norm / (total + 1e-6), 1.0)
    return [g * coef.astype(g.dtype) for g in grads_tree_leaves], total
