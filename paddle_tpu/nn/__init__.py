"""paddle_tpu.nn (reference surface: python/paddle/nn/)."""
from . import functional
from . import initializer
from . import utils
from .layer.layers import (Layer, LayerList, ParameterList, Sequential)
from .layer.common import (AlphaDropout, Bilinear, ChannelShuffle,
                           CosineSimilarity, Dropout, Dropout2D, Dropout3D,
                           Embedding, Flatten, Fold, Identity, Linear, Pad1D,
                           Pad2D, Pad3D, PixelShuffle, PixelUnshuffle, Unfold,
                           Upsample, UpsamplingBilinear2D, UpsamplingNearest2D,
                           ZeroPad2D)
from .layer.conv import (Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose,
                         Conv3D, Conv3DTranspose)
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                         GroupNorm, InstanceNorm1D, InstanceNorm2D,
                         InstanceNorm3D, LayerNorm, LocalResponseNorm, RMSNorm,
                         SpectralNorm, SyncBatchNorm)
from .layer.activation import (CELU, ELU, GELU, GLU, Hardshrink, Hardsigmoid,
                               Hardswish, Hardtanh, LeakyReLU, LogSigmoid,
                               LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6,
                               RReLU, SELU, Sigmoid, Silu, Softmax, Softplus,
                               Softshrink, Softsign, Swish, Tanh, Tanhshrink,
                               ThresholdedReLU)
from .layer.pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D,
                            AdaptiveAvgPool3D, AdaptiveMaxPool1D,
                            AdaptiveMaxPool2D, AvgPool1D, AvgPool2D, AvgPool3D,
                            MaxPool1D, MaxPool2D, MaxPool3D)
from .layer.loss import (BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss,
                         CrossEntropyLoss, CTCLoss, HingeEmbeddingLoss,
                         KLDivLoss, L1Loss, MarginRankingLoss, MSELoss,
                         NLLLoss, SmoothL1Loss, TripletMarginLoss)
from .layer.transformer import (MultiHeadAttention, Transformer,
                                TransformerDecoder, TransformerDecoderLayer,
                                TransformerEncoder, TransformerEncoderLayer)
from .layer.rnn import (GRU, GRUCell, LSTM, LSTMCell, RNN, BiRNN, SimpleRNN,
                        SimpleRNNCell, RNNCellBase)
from .parallel import DataParallel

from ..core.tensor import Parameter  # noqa: F401 — nn.Parameter alias


class ParameterAttr:
    """paddle.ParamAttr equivalent — carries name/initializer/lr/regularizer."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


ParamAttr = ParameterAttr


def clip_grad_norm_(parameters, max_norm, norm_type=2.0):
    from .clip import clip_grad_norm_ as _impl
    return _impl(parameters, max_norm, norm_type)


class ClipGradByNorm:
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm


class ClipGradByGlobalNorm:
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm


class ClipGradByValue:
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min
