"""DataParallel wrapper (reference: python/paddle/fluid/dygraph/parallel.py:413).

TPU-native: under jax's single-controller model, data parallelism is a
sharding of the batch axis over the mesh — gradients come back globally
summed by XLA (the reference needed an EagerReducer with bucketed NCCL
allreduce; SURVEY.md §2.2 row 1).  This wrapper therefore:

* eager path: runs the inner layer unchanged on one device (single-process
  semantics identical to reference single-rank), and
* compiled path: ``paddle_tpu.jit.TrainStep`` / ``distributed.parallelize``
  shard the batch axis of its inputs over the 'dp' mesh axis.
"""
from __future__ import annotations

from .layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self.find_unused_parameters = find_unused_parameters
        self.group = group

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, state_dict, *a, **k):
        return self._layers.set_state_dict(state_dict, *a, **k)

    def scale_loss(self, loss):
        # XLA handles gradient averaging via mean-over-batch + psum; no-op
        return loss

    def apply_collective_grads(self):
        # grads are already globally reduced on the compiled path; on the
        # eager single-process path there is nothing to reduce
        pass
