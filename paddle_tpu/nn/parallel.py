"""DataParallel wrapper (reference: python/paddle/fluid/dygraph/parallel.py:413).

TPU-native: under jax's single-controller model, data parallelism is a
sharding of the batch axis over the mesh — gradients come back globally
summed by XLA (the reference needed an EagerReducer with bucketed NCCL
allreduce; SURVEY.md §2.2 row 1).  This wrapper therefore:

* eager path: runs the inner layer unchanged on one device (single-process
  semantics identical to reference single-rank), and
* compiled path: ``paddle_tpu.jit.TrainStep`` / ``distributed.parallelize``
  shard the batch axis of its inputs over the 'dp' mesh axis.
"""
from __future__ import annotations

import jax
import numpy as np

from .layer.layers import Layer


class DataParallel(Layer):
    """Multi-process eager DP keeps the reference semantics
    (parallel.py:413): parameters are broadcast from rank 0 at wrap time
    (sync_params_buffers ≈ parallel.py:369) and ``apply_collective_grads``
    mean-reduces gradients across processes after ``backward()`` — the
    EagerReducer's job (reducer.h:87), done with one fused cross-process
    psum via multihost_utils instead of bucketed NCCL.  Single-process
    (the normal TPU pjit topology) both are no-ops."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self.find_unused_parameters = find_unused_parameters
        self.group = group
        self._nprocs = jax.process_count()
        if self._nprocs > 1:
            self.sync_params_buffers()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, state_dict, *a, **k):
        return self._layers.set_state_dict(state_dict, *a, **k)

    def sync_params_buffers(self):
        """Broadcast rank-0 parameters/buffers to every process
        (reference: parallel.py:369)."""
        if self._nprocs <= 1:
            return
        from jax.experimental import multihost_utils
        state = self._layers.state_dict()
        arrays = {k: np.asarray(t._array) for k, t in state.items()}
        synced = multihost_utils.broadcast_one_to_all(arrays)
        for k, t in state.items():
            t._array = jax.numpy.asarray(synced[k]).astype(t._array.dtype)

    def scale_loss(self, loss):
        # gradient averaging happens in apply_collective_grads (mean), so
        # the loss itself is not rescaled — same net semantics as the
        # reference's scale+sum
        return loss

    def apply_collective_grads(self):
        """Mean-reduce every parameter gradient across processes (the
        EagerReducer allreduce, reducer.h:87).  Call between backward()
        and optimizer.step() — no-op single-process.

        Keyed by parameter NAME over the full trainable set, with a
        has-grad flag per rank: ranks that skipped a conditional branch
        (find_unused_parameters case) contribute zeros and the sum divides
        by world size, matching the reference's allreduce-mean — positional
        keying after filtering would silently pair different parameters
        across ranks."""
        if self._nprocs <= 1:
            return
        from jax.experimental import multihost_utils
        named = [(name, p) for name, p in self._layers.named_parameters()
                 if not p.stop_gradient]
        if not named:
            return
        payload = {}
        for name, p in named:
            if p.grad is not None:
                payload[name] = (np.float32(1.0), np.asarray(p.grad._array))
            else:
                payload[name] = (np.float32(0.0),
                                 np.zeros(tuple(p.shape),
                                          np.asarray(p._array).dtype))
        # process_allgather stacks per-process leaves along axis 0
        gathered = multihost_utils.process_allgather(payload)
        for name, p in named:
            counts, grads = gathered[name]
            if float(np.sum(counts)) == 0:
                continue  # unused on every rank: leave grad as-is
            g = np.sum(grads, axis=0) / self._nprocs
            if p.grad is None:
                from ..core.tensor import Tensor
                p.grad = Tensor(jax.numpy.asarray(g))
            else:
                p.grad._array = jax.numpy.asarray(g).astype(
                    p.grad._array.dtype)
