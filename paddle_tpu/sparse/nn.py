"""paddle.sparse.nn — sparse layers (reference:
python/paddle/sparse/nn at v2.3-dev: ReLU + functional)."""
from __future__ import annotations


class ReLU:
    def __init__(self, name=None):
        pass

    def __call__(self, x):
        from . import relu
        return relu(x)


class functional:
    @staticmethod
    def relu(x):
        from . import relu as _relu
        return _relu(x)
