"""paddle.sparse — COO/CSR sparse tensors (reference surface:
python/paddle/sparse/ at the v2.3-dev point: sparse_coo_tensor,
sparse_csr_tensor, to_dense/to_sparse conversions, elementwise relu/sqrt,
matmul; C++ phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h).

TPU-native: backed by jax.experimental.sparse.BCOO — XLA compiles gather/
scatter-based sparse kernels.  CSR is stored in CSR component form and
converted to BCOO for compute (TPU has no native CSR unit; BCOO's
batched-COO layout is the form XLA vectorises well).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from . import nn  # noqa: F401

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "is_same_shape", "nn",
           "add", "subtract", "multiply", "divide", "matmul", "relu", "sqrt",
           "sin", "tanh", "abs", "pow", "neg", "cast", "transpose"]


def _arr(x):
    if isinstance(x, Tensor):
        return x._array
    return jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor (reference: phi/core/sparse_coo_tensor.h)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- paddle surface ------------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, -1, -2))

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        dense = np.asarray(self._bcoo.todense())
        return _dense_to_csr(dense)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor (reference: phi/core/sparse_csr_tensor.h)."""

    def __init__(self, crows, cols, values, shape):
        self.crows_ = jnp.asarray(crows, jnp.int64)
        self.cols_ = jnp.asarray(cols, jnp.int64)
        self.values_ = _arr(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self.values_.dtype

    @property
    def nnz(self):
        return int(self.cols_.shape[0])

    def crows(self):
        return Tensor(self.crows_)

    def cols(self):
        return Tensor(self.cols_)

    def values(self):
        return Tensor(self.values_)

    def _to_bcoo(self) -> jsparse.BCOO:
        counts = jnp.diff(self.crows_)
        rows = jnp.repeat(jnp.arange(self._shape[0]), counts,
                          total_repeat_length=self.cols_.shape[0])
        idx = jnp.stack([rows, self.cols_], axis=1)
        return jsparse.BCOO((self.values_, idx), shape=self._shape)

    def to_dense(self):
        return Tensor(self._to_bcoo().todense())

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._to_bcoo())

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """reference: paddle.sparse.sparse_coo_tensor — indices (ndim, nnz)."""
    idx = jnp.asarray(_arr(indices), jnp.int32)
    vals = _arr(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(jnp.max(idx, axis=1)))
    bcoo = jsparse.BCOO((vals, jnp.swapaxes(idx, 0, 1)),
                        shape=tuple(int(s) for s in shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    """reference: paddle.sparse.sparse_csr_tensor."""
    vals = _arr(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    return SparseCsrTensor(_arr(crows), _arr(cols), vals, shape)


def _dense_to_csr(dense: np.ndarray) -> SparseCsrTensor:
    if dense.ndim != 2:
        raise ValueError("CSR requires a 2-D tensor")
    rows, cols = np.nonzero(dense)
    values = dense[rows, cols]
    crows = np.zeros(dense.shape[0] + 1, np.int64)
    np.add.at(crows[1:], rows, 1)
    crows = np.cumsum(crows)
    return SparseCsrTensor(crows, cols, values, dense.shape)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


# -- functional ops ----------------------------------------------------------

def _coo(x) -> jsparse.BCOO:
    if isinstance(x, SparseCooTensor):
        return x._bcoo
    if isinstance(x, SparseCsrTensor):
        return x._to_bcoo()
    raise TypeError(f"expected a sparse tensor, got {type(x).__name__}")


def _unary(fn, x):
    """Elementwise op applied to stored values only (zeros preserved —
    valid for fn with fn(0)=0, the reference's sparse unary set).
    Pattern-preserving: O(nnz), stays on device for both layouts."""
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x.crows_, x.cols_, fn(x.values_), x._shape)
    bcoo = _coo(x)
    return SparseCooTensor(
        jsparse.BCOO((fn(bcoo.data), bcoo.indices), shape=bcoo.shape))


def relu(x):
    return _unary(jax.nn.relu, x)


def sqrt(x):
    return _unary(jnp.sqrt, x)


def sin(x):
    return _unary(jnp.sin, x)


def tanh(x):
    return _unary(jnp.tanh, x)


def abs(x):
    return _unary(jnp.abs, x)


def neg(x):
    return _unary(jnp.negative, x)


def pow(x, factor):
    return _unary(lambda v: jnp.power(v, factor), x)


def cast(x, index_dtype=None, value_dtype=None):
    bcoo = _coo(x)
    data = bcoo.data
    idx = bcoo.indices
    if value_dtype is not None:
        from ..core.dtype import convert_dtype
        data = data.astype(convert_dtype(value_dtype))
    if index_dtype is not None:
        from ..core.dtype import convert_dtype
        idx = idx.astype(convert_dtype(index_dtype))
    return SparseCooTensor(jsparse.BCOO((data, idx), shape=bcoo.shape))


def transpose(x, perm):
    return SparseCooTensor(_coo(x).transpose(tuple(perm)))


def _binary(fn, x, y):
    # sparse-sparse elementwise: dense compute then re-sparsify — small
    # operand sizes in the reference's API tests; a fused BCOO union kernel
    # is an optimisation left for when a workload needs it
    bx, by = _coo(x), _coo(y)
    dense = fn(bx.todense(), by.todense())
    return SparseCooTensor(jsparse.BCOO.fromdense(dense))


def add(x, y):
    return _binary(jnp.add, x, y)


def subtract(x, y):
    return _binary(jnp.subtract, x, y)


def multiply(x, y):
    return _binary(jnp.multiply, x, y)


def divide(x, y):
    """Elementwise divide evaluated at x's stored positions (the reference
    kernel assumes matching sparsity; positions where y has no entry divide
    by zero and yield inf/nan, like the dense semantics)."""
    bx, by = _coo(x), _coo(y)
    ydense = by.todense()
    yv = ydense[tuple(bx.indices[:, d] for d in range(bx.indices.shape[1]))]
    return SparseCooTensor(jsparse.BCOO((bx.data / yv, bx.indices),
                                        shape=bx.shape))


def matmul(x, y):
    """sparse @ dense -> dense (reference: paddle.sparse.matmul)."""
    bx = _coo(x)
    yd = y._array if isinstance(y, Tensor) else _arr(y)
    return Tensor(bx @ yd)
