"""Exporters: Prometheus text format, JSONL snapshots, chrome-trace marks.

Three consumers, one :meth:`Registry.snapshot` shape:

* :func:`to_prometheus` — the text exposition format.  Histograms export as
  Prometheus *summaries* (``_count`` / ``_sum`` + ``quantile=`` series):
  the registry already computes p50/p95/p99 from its fixed log buckets, and
  a summary line per quantile beats shipping 256 cumulative ``le=`` buckets
  per histogram over every scrape.
* :class:`JsonlExporter` — appends ``{"ts": ..., "metrics": snapshot}``
  lines; the ``python -m paddle_tpu.observability`` CLI and the CI bench
  schema both read this shape.
* :func:`inject_profiler_marks` — pushes the current counter/gauge values
  into the host profiler's metric-mark buffer so a chrome://tracing export
  shows metric counter tracks time-aligned with the RecordEvent spans.
"""
from __future__ import annotations

import json
import re
from typing import Optional

from . import registry as _registry

__all__ = ["to_prometheus", "JsonlExporter", "snapshot_line",
           "inject_profiler_marks"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join('%s="%s"' % (_prom_name(k),
                                  str(v).replace("\\", "\\\\")
                                  .replace('"', '\\"').replace("\n", "\\n"))
                     for k, v in sorted(labels.items()))
    return "{%s}" % inner


def to_prometheus(reg: Optional["_registry.Registry"] = None,
                  snapshot: Optional[dict] = None) -> str:
    """Render a registry (or a pre-taken snapshot) as Prometheus text."""
    if snapshot is None:
        snapshot = (reg or _registry.default_registry()).snapshot()
    lines = []
    for name, entry in snapshot.items():
        pname = _prom_name(name)
        kind = entry["type"]
        prom_type = {"counter": "counter", "gauge": "gauge",
                     "histogram": "summary"}[kind]
        lines.append("# TYPE %s %s" % (pname, prom_type))
        for series in entry["series"]:
            labels = series.get("labels", {})
            if kind == "histogram":
                for q in ("p50", "p95", "p99"):
                    ql = dict(labels)
                    ql["quantile"] = "0.%s" % q[1:]
                    lines.append("%s%s %s"
                                 % (pname, _prom_labels(ql), series[q]))
                lines.append("%s_count%s %s"
                             % (pname, _prom_labels(labels),
                                series["count"]))
                lines.append("%s_sum%s %s"
                             % (pname, _prom_labels(labels), series["sum"]))
            else:
                lines.append("%s%s %s"
                             % (pname, _prom_labels(labels),
                                series["value"]))
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot_line(reg: Optional["_registry.Registry"] = None) -> str:
    """One JSONL line: ``{"ts": <unix seconds>, "metrics": snapshot}``."""
    reg = reg or _registry.default_registry()
    return json.dumps({"ts": _registry.now(), "metrics": reg.snapshot()},
                      sort_keys=True)


class JsonlExporter:
    """Append-only JSONL snapshot writer (one line per :meth:`write`)."""

    def __init__(self, path: str):
        self.path = path

    def write(self, reg: Optional["_registry.Registry"] = None) -> str:
        line = snapshot_line(reg)
        with open(self.path, "a") as f:
            f.write(line + "\n")
        return line


def inject_profiler_marks(reg: Optional["_registry.Registry"] = None,
                          ts_ns: Optional[int] = None) -> int:
    """Push every counter/gauge value (and histogram counts) into the host
    profiler's metric-mark buffer as chrome-trace counter events; returns
    how many marks were written.  Called by ``Profiler.stop()`` so every
    trace export carries the metric state alongside the spans."""
    import time

    from .. import profiler as _prof

    reg = reg or _registry.default_registry()
    if not reg.enabled:
        return 0
    if ts_ns is None:
        ts_ns = time.perf_counter_ns()
    n = 0
    for name, entry in reg.snapshot().items():
        for series in entry["series"]:
            labels = series.get("labels", {})
            suffix = ("{%s}" % ",".join("%s=%s" % kv
                                        for kv in sorted(labels.items()))
                      if labels else "")
            value = (series["count"] if entry["type"] == "histogram"
                     else series["value"])
            _prof._metric_marks.append((name + suffix, ts_ns, float(value)))
            n += 1
    # backstop: keep only the newest _MARKS_CAP marks if nothing drains
    del _prof._metric_marks[:-_prof._MARKS_CAP]
    return n
