"""Cross-host telemetry aggregation: per-host snapshot publish + the
host-0 cluster view with straggler attribution.

Everything the observability stack records so far — registry, tracing,
flight, the HBM ledger, liveness beacons — is **single-host**, while
training is multi-host and the serving engine is tp=N.  A lopsided
fleet (one host's step time 40% over the median drags EVERY synchronous
step to its pace) or a host that silently stopped publishing is
invisible from any one worker's metrics.

Two halves:

* :class:`HostPublisher` — every host periodically publishes one JSON
  **telemetry snapshot** (full registry snapshot + liveness beacon ages
  + step-time summaries extracted from the step/batch/decode-step
  histograms) to the PR-4 distributed store under
  ``paddle_tpu/telemetry/<host>``.  The store client already wraps
  every op in the retry policy (transient resets reconnect + retry), so
  publication survives a flaky rendezvous link; a publish that
  exhausts retries is logged and skipped — telemetry must never take
  down training.
* :func:`merge_cluster` (host 0, or the ``cluster`` CLI) — fetches
  every host's newest snapshot, merges the **cluster view**
  (per-host step p50/p95, beacon stalls, staleness, missing hosts) and
  runs straggler detection: a host whose step-time p50 exceeds the
  cluster median by more than ``pct`` percent (default 25,
  ``PADDLE_TPU_STRAGGLER_PCT``) is flagged and the catalog'd
  ``liveness.straggler{host=}`` gauge is set per host (1 flagged / 0
  not) so a scraper alarms on it.  A host that never published is its
  own loud row — "missing" IS the signal for a wedged worker.

``python -m paddle_tpu.observability cluster --master host:port
--world N`` renders the merged table from any machine that can reach
the store (exit 2 when NO host published — never silent green; exit 1
when some are missing).
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import liveness as _liveness
from . import registry as _registry
from .liveness import _env_float

__all__ = [
    "KEY_PREFIX", "STEP_TIME_METRICS", "host_snapshot", "HostPublisher",
    "fetch_cluster", "merge_docs", "merge_cluster", "format_cluster",
    "straggler_pct_default",
]

#: store key prefix; one key per host, newest snapshot wins (set()
#: overwrites — the view is "current state", not a history)
KEY_PREFIX = "paddle_tpu/telemetry/"

#: step-time sources for straggler attribution, in preference order:
#: the first histogram with samples on a host names that host's pace
STEP_TIME_METRICS = ("train.step_seconds", "train.batch_seconds",
                     "serving.decode_step_seconds")

_FORMAT = "paddle_tpu-telemetry-v1"


def straggler_pct_default() -> float:
    # degrade-loudly parse (liveness._env_float): a typo'd knob must
    # never crash host-0's merge loop or the cluster CLI
    v = _env_float("PADDLE_TPU_STRAGGLER_PCT")
    return v if v is not None else 25.0


def _host_id(host: Optional[int]) -> int:
    if host is not None:
        return int(host)
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def _step_summaries(metrics: dict) -> Dict[str, dict]:
    """{metric: {count, sum, p50, p95, p99}} for every step-time
    histogram with samples in a registry snapshot."""
    out = {}
    for name in STEP_TIME_METRICS:
        entry = metrics.get(name)
        if not entry or entry.get("type") != "histogram":
            continue
        for series in entry.get("series", ()):
            if series.get("count"):
                out[name] = {k: series[k] for k in
                             ("count", "sum", "p50", "p95", "p99")}
                break
    return out


def _stall_counts(metrics: dict) -> Dict[str, float]:
    entry = metrics.get("liveness.stalls")
    if not entry:
        return {}
    return {s.get("labels", {}).get("beacon", "?"): s.get("value", 0.0)
            for s in entry.get("series", ()) if s.get("value")}


def host_snapshot(host: Optional[int] = None) -> dict:
    """This host's publishable telemetry document: the full registry
    snapshot plus the derived views the merger needs (step-time
    summaries, beacon ages, stall counts)."""
    metrics = _registry.default_registry().snapshot()
    return {
        "format": _FORMAT,
        "host": _host_id(host),
        "pid": os.getpid(),
        "wall_ts": time.time(),
        "beacons": _liveness.state(),
        "step_times": _step_summaries(metrics),
        "stalls": _stall_counts(metrics),
        "metrics": metrics,
    }


class HostPublisher:
    """Periodic snapshot publisher.  ``publish_once()`` is the unit the
    thread loops over (tests call it directly); the store's own retry
    policy covers transient link failures, and a publish that still
    fails is logged and skipped — telemetry must never kill training."""

    def __init__(self, store, host: Optional[int] = None,
                 interval: Optional[float] = None):
        self.store = store
        self.host = _host_id(host)
        if interval is None:
            # degrade-loudly parse: a typo'd interval must not crash
            # worker startup on every host ("telemetry never takes
            # down training")
            v = _env_float("PADDLE_TPU_TELEMETRY_INTERVAL")
            interval = v if v is not None else 10.0
        self.interval = float(interval)
        self.published = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # guards `published`: publish_once runs on the loop thread AND
        # on whatever thread calls it directly (tests, stop(final=True))
        self._publish_lock = threading.Lock()

    @property
    def key(self) -> str:
        return KEY_PREFIX + str(self.host)

    def publish_once(self) -> str:
        doc = host_snapshot(self.host)
        self.store.set(self.key, json.dumps(doc, sort_keys=True).encode())
        with self._publish_lock:
            self.published += 1
        return self.key

    def start(self) -> "HostPublisher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="telemetry-publisher", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0, final: bool = True):
        """Stop the loop; ``final=True`` publishes one last snapshot so
        the cluster view holds this host's exit state."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                # the loop is wedged inside a store op: publishing the
                # final snapshot NOW would race it on the same key, and
                # waiting for it would block shutdown indefinitely —
                # skip the final publish, keep stop() bounded
                sys.stderr.write("[telemetry] publisher still busy after "
                                 "%.1fs; skipping final publish\n"
                                 % timeout)
                self._thread = None
                return
        self._thread = None
        if final:
            try:
                self.publish_once()
            except Exception as e:
                sys.stderr.write("[telemetry] final publish failed: %r\n"
                                 % (e,))

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.publish_once()
            except Exception as e:
                # RetryError after the store policy gave up, or a torn
                # store: drop THIS snapshot, keep the loop alive
                sys.stderr.write("[telemetry] publish failed "
                                 "(skipping this interval): %r\n" % (e,))


# ---------------------------------------------------------------------------
# host-0 merge + straggler detection
# ---------------------------------------------------------------------------

def fetch_cluster(store, world_size: int
                  ) -> Tuple[Dict[int, dict], List[int]]:
    """Every host's newest snapshot from the store; hosts that never
    published (or published garbage) land in ``missing``."""
    docs: Dict[int, dict] = {}
    missing: List[int] = []
    for h in range(int(world_size)):
        try:
            raw = store.get(KEY_PREFIX + str(h), wait=False)
            doc = json.loads(raw.decode("utf-8"))
            if doc.get("format") != _FORMAT:
                raise ValueError("unknown telemetry format %r"
                                 % doc.get("format"))
            docs[h] = doc
        except KeyError:
            missing.append(h)
        except (ValueError, UnicodeDecodeError):
            missing.append(h)
    return docs, missing


def merge_docs(docs: Dict[int, dict], world_size: int,
               pct: Optional[float] = None,
               set_gauges: bool = True) -> dict:
    """Merge per-host snapshots into the cluster view and flag
    stragglers: hosts whose step-time p50 exceeds the cluster median by
    more than ``pct`` percent.  With ``set_gauges`` (host-0 usage) the
    ``liveness.straggler{host=}`` gauge is set 1/0 per published host
    so a scraper can alarm without parsing the table."""
    if pct is None:
        pct = straggler_pct_default()
    now = time.time()
    hosts: Dict[int, dict] = {}
    paced: List[Tuple[int, float]] = []
    for h, doc in sorted(docs.items()):
        step_metric, p50, p95, count = None, None, None, 0
        for name in STEP_TIME_METRICS:
            s = doc.get("step_times", {}).get(name)
            if s:
                step_metric = name
                p50, p95 = s["p50"], s["p95"]
                count = s["count"]
                break
        beacons = doc.get("beacons", {})
        hosts[h] = {
            "wall_ts": doc.get("wall_ts"),
            "staleness_s": round(max(now - doc.get("wall_ts", now), 0.0),
                                 3),
            "step_metric": step_metric,
            "step_p50_s": p50,
            "step_p95_s": p95,
            "step_count": count,
            "stalled_beacons": sorted(
                n for n, b in beacons.items() if b.get("stalled")),
            "stalls": doc.get("stalls", {}),
        }
        if p50 is not None and count > 0:
            paced.append((h, float(p50)))
    median = statistics.median([p for _h, p in paced]) if paced else None
    stragglers = []
    if median is not None and len(paced) >= 2 and median > 0:
        threshold = median * (1.0 + pct / 100.0)
        stragglers = sorted(h for h, p in paced if p > threshold)
    for h in hosts:
        hosts[h]["straggler"] = h in stragglers
    if set_gauges:
        g = _registry.gauge("liveness.straggler", ("host",))
        for h in hosts:
            g.labels(host=str(h)).set(1.0 if h in stragglers else 0.0)
    return {
        "format": "paddle_tpu-cluster-v1",
        "wall_ts": now,
        "world_size": int(world_size),
        "hosts": hosts,
        "missing": sorted(set(range(int(world_size))) - set(docs)),
        "median_step_s": median,
        "straggler_pct": pct,
        "stragglers": stragglers,
    }


def merge_cluster(store, world_size: int, pct: Optional[float] = None,
                  set_gauges: bool = True) -> dict:
    docs, _missing = fetch_cluster(store, world_size)
    return merge_docs(docs, world_size, pct=pct, set_gauges=set_gauges)


def format_cluster(doc: dict) -> str:
    """The human table the ``cluster`` CLI prints."""
    lines = []
    med = doc.get("median_step_s")
    lines.append(
        "cluster view: %d/%d hosts published, median step %s, "
        "straggler threshold +%.0f%%"
        % (len(doc["hosts"]), doc["world_size"],
           ("%.4fs" % med) if med is not None else "n/a",
           doc["straggler_pct"]))
    header = ("host", "step p50", "p95", "steps", "vs median",
              "stale", "stalled beacons", "flags")
    rows = [header]
    for h in sorted(doc["hosts"]):
        info = doc["hosts"][h]
        p50 = info["step_p50_s"]
        vs = ("%+.0f%%" % ((p50 / med - 1.0) * 100.0)
              if p50 is not None and med else "-")
        flags = []
        if info.get("straggler"):
            flags.append("STRAGGLER")
        if info.get("stalled_beacons"):
            flags.append("STALLED")
        rows.append((
            str(h),
            ("%.4fs" % p50) if p50 is not None else "-",
            ("%.4fs" % info["step_p95_s"])
            if info["step_p95_s"] is not None else "-",
            str(info["step_count"]),
            vs,
            "%.0fs" % info["staleness_s"],
            ",".join(info["stalled_beacons"]) or "-",
            ",".join(flags) or "-",
        ))
    for h in doc["missing"]:
        rows.append((str(h), "-", "-", "-", "-", "-", "-", "MISSING"))
    widths = [max(len(r[i]) for r in rows) for i in range(len(header))]
    lines.extend("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
                 for r in rows)
    if doc["stragglers"]:
        lines.append("stragglers: %s"
                     % ", ".join("host %d" % h for h in doc["stragglers"]))
    if doc["missing"]:
        lines.append("MISSING (never published — wedged or dead?): %s"
                     % ", ".join("host %d" % h for h in doc["missing"]))
    return "\n".join(lines)
