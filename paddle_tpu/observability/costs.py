"""Compiled-program cost & memory reports — XLA's own numbers, surfaced.

COVERAGE.md §2.3 declared the reference framework's op-level cost model a
non-goal *because* "XLA cost analysis runs on the actual lowered program".
This module cashes that claim: every lowered/compiled entry point can be
priced with the compiler's own ``cost_analysis()`` (FLOPs, bytes accessed,
transcendentals) and ``memory_analysis()`` (argument/output/temp/alias/
generated-code bytes), and the canonical trace-audit registry
(:mod:`paddle_tpu.analysis.trace.programs`) is priced wholesale:

* :func:`registry_reports` — one :class:`ProgramReport` per canonical
  program (the ``python -m paddle_tpu.observability programs`` CLI);
* TPU506 (:mod:`paddle_tpu.analysis.trace.hbm_budget`) compares each
  report's derived peak-HBM against a declared per-program budget — the
  post-compile complement to TPU504's pre-compile VMEM estimate;
* :func:`cost_block` — the schema'd ``cost`` block bench.py /
  bench_decode.py attach to their JSON lines ({flops, hbm_bytes,
  peak_bytes, mfu, bw_util}), with MFU / HBM-bandwidth-utilization
  derived only when on-chip step timings exist (CPU lines carry the
  static fields and ``null`` utilizations — the trajectory gate
  validates their shape but never perf-gates them).

Graceful degradation is the contract, not an accident: backends report
different subsets (CPU's ``generated_code_size_in_bytes`` is 0, TPU adds
real code/temp sizes; Pallas kernels price their interpret-mode lowering
off-chip), ``cost_analysis()`` is list-shaped on jax <= 0.4.x (ONE compat
shim here — :func:`cost_analysis_dict` — which ``hapi.flops`` also
routes through), and a missing field is ``None``, never a guess.

Derived peak: XLA 0.4.x exposes no single peak-memory scalar, so
``peak_bytes = argument + output + temp - alias`` — the executable's
whole-BUFFER high-water bound (donated/aliased buffers counted once;
generated code is reported separately and excluded on purpose: code
size varies wildly per backend and is not the data-buffer regression
vector the TPU506 budgets gate).  The budgets are sized against this
same derivation, so the gate is self-consistent.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ProgramReport", "cost_analysis_dict", "memory_analysis_dict",
    "report_from_compiled", "compile_program", "report_for_program",
    "registry_reports", "peak_flops", "peak_hbm_bandwidth", "mfu",
    "bw_util", "cost_block", "format_table",
]

# ---------------------------------------------------------------------------
# per-part peak specs (published numbers, per chip); substring-matched
# against jax's device_kind.  Overridable for new parts / corrected specs
# via PADDLE_TPU_PEAK_FLOPS / PADDLE_TPU_PEAK_HBM_BW (floats, per chip).
# ---------------------------------------------------------------------------

#: bf16 peak FLOP/s per chip by device-kind substring (lowercase).
PEAK_FLOPS_BY_KIND = (
    ("v6e", 918e12), ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 46e12),
)

#: HBM bandwidth bytes/s per chip by device-kind substring (lowercase).
PEAK_HBM_BW_BY_KIND = (
    ("v6e", 1640e9), ("v5p", 2765e9),
    ("v5 lite", 819e9), ("v5e", 819e9),
    ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
)


def _kind_lookup(table, kind: Optional[str]) -> Optional[float]:
    if not kind:
        return None
    kind = kind.lower()
    for sub, v in table:
        if sub in kind:
            return v
    return None


def _device_kind() -> Optional[str]:
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:
        return None


def peak_flops(device_kind: Optional[str] = None) -> Optional[float]:
    """Peak bf16 FLOP/s of one chip (None off-chip / unknown part)."""
    env = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    return _kind_lookup(PEAK_FLOPS_BY_KIND,
                        device_kind or _device_kind())


def peak_hbm_bandwidth(device_kind: Optional[str] = None
                       ) -> Optional[float]:
    """Peak HBM bytes/s of one chip (None off-chip / unknown part)."""
    env = os.environ.get("PADDLE_TPU_PEAK_HBM_BW")
    if env:
        return float(env)
    return _kind_lookup(PEAK_HBM_BW_BY_KIND,
                        device_kind or _device_kind())


def mfu(flops: Optional[float], step_seconds: Optional[float],
        device_kind: Optional[str] = None) -> Optional[float]:
    """Model FLOPs utilization of one compiled step: program FLOPs /
    (step wall seconds * chip peak).  None whenever any input is
    unknown — a fabricated 0.0 would enter the trajectory as a datum."""
    peak = peak_flops(device_kind)
    if not flops or not step_seconds or step_seconds <= 0 or not peak:
        return None
    return flops / (step_seconds * peak)


def bw_util(hbm_bytes: Optional[float], step_seconds: Optional[float],
            device_kind: Optional[str] = None) -> Optional[float]:
    """HBM bandwidth utilization: program bytes-accessed / (step wall
    seconds * chip peak bandwidth)."""
    peak = peak_hbm_bandwidth(device_kind)
    if not hbm_bytes or not step_seconds or step_seconds <= 0 or not peak:
        return None
    return hbm_bytes / (step_seconds * peak)


# ---------------------------------------------------------------------------
# extraction (THE compat shims — hapi.flops routes through these too)
# ---------------------------------------------------------------------------

def cost_analysis_dict(compiled, strict: bool = False) -> Dict[str, float]:
    """``compiled.cost_analysis()`` as ONE flat dict.

    The single 0.4.x compat shim: jax <= 0.4.x returns a list with one
    dict per device — identical replicas on a single-program compile, so
    the first is taken; newer jax returns the dict directly.  A backend
    that reports nothing yields ``{}``; a RAISING backend is swallowed
    to ``{}`` only under ``strict=False`` (the ProgramReport path, which
    carries available/note fields for the degradation) — ``strict=True``
    propagates it for callers with no such channel (``hapi.flops``
    must error, not answer 0, when the analysis itself fails)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        if strict:
            raise
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


#: memory_analysis attributes extracted when present (per-backend subset)
_MEMORY_FIELDS = (
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("temp_bytes", "temp_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
    ("generated_code_bytes", "generated_code_size_in_bytes"),
)


def memory_analysis_dict(compiled) -> Dict[str, int]:
    """``compiled.memory_analysis()`` as a plain dict of the fields this
    backend reports (missing attributes are omitted, not guessed)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    out: Dict[str, int] = {}
    for name, attr in _MEMORY_FIELDS:
        v = getattr(ma, attr, None)
        if v is not None:
            out[name] = int(v)
    return out


# ---------------------------------------------------------------------------
# partitioned-collective pricing (ISSUE 12): XLA's cost_analysis does not
# break bytes out by collective, so the SPMD-partitioned HLO text is the
# source — every all-reduce/all-gather/... instruction's result shape,
# summed.  The serving engine's per-step collective-bytes counter and the
# TPU503 SPMD audit both read this.
# ---------------------------------------------------------------------------

_COLLECTIVE_HLO_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                       "collective-permute", "all-to-all")

_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

#: `dtype[d0,d1,...]` shape tokens in an HLO instruction's result slot
_HLO_SHAPE_RE = None


def _hlo_shape_bytes(span: str) -> int:
    """Sum the bytes of every ``dtype[dims]`` shape token in ``span``
    (handles tuple-shaped results like async collective starts)."""
    global _HLO_SHAPE_RE
    import re
    if _HLO_SHAPE_RE is None:
        _HLO_SHAPE_RE = re.compile(
            r"\b(%s)\[([\d,]*)\]" % "|".join(_HLO_DTYPE_BYTES))
    total = 0
    for dt, dims in _HLO_SHAPE_RE.findall(span):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _HLO_DTYPE_BYTES[dt]
    return total


def collective_stats(compiled) -> Optional[Dict[str, Any]]:
    """``{"ops": N, "bytes": B, "by_kind": {...}}`` over the collective
    instructions of a compiled (post-SPMD-partitioning) executable's
    optimized HLO, or ``None`` when the backend exposes no HLO text.
    ``bytes`` sums each collective's RESULT shape — the data one step
    moves over the mesh.  ``by_kind`` breaks both figures out per HLO
    op (``{"all-gather": {"ops": n, "bytes": b}, ...}``) — ISSUE 20
    reads it as a *launches vs bytes* split: a decomposed overlap ring
    replaces ONE all-gather with ``chunks*(n-1)`` collective-permutes
    whose summed result bytes stay in the same band, so a raw op-count
    diff would read the rewrite as an Nx collective regression while
    the by-kind view shows what actually happened (monolithic kind
    GONE, permute chain present, bytes ~flat).  Async pairs are counted
    once, at the ``-done`` (whose result is the OUTPUT buffer alone; a
    ``-start``'s tuple result carries the input buffer and context
    fields too, which would over-price an async lowering ~1.5x vs the
    sync form of the same program).  Caveat: these are STATIC
    instruction counts — a collective inside a while/scan body is
    priced once, not per trip (the serving decode's per-layer walk is a
    python loop, so its entries unroll; priced exactly — but the
    overlap rings' chunk loops are also fully unrolled at trace time,
    so every hop of a chunked ring IS a distinct priced instruction)."""
    import re
    try:
        text = compiled.as_text()
    except Exception:
        return None
    if not isinstance(text, str):
        return None
    by_kind: Dict[str, Dict[str, int]] = {}

    def _tally(kind, nbytes):
        slot = by_kind.setdefault(kind, {"ops": 0, "bytes": 0})
        slot["ops"] += 1
        slot["bytes"] += nbytes

    names = "|".join(_COLLECTIVE_HLO_OPS)
    head = (r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(.+?)\s+(" + names + r")")
    sync_pat = re.compile(head + r"\(")
    done_pat = re.compile(head + r"-done\(")
    start_pat = re.compile(head + r"-start\(")
    for line in text.splitlines():
        m = done_pat.match(line)
        if m:
            _tally(m.group(2), _hlo_shape_bytes(m.group(1)))
            continue
        if start_pat.match(line):
            continue    # priced at its -done
        m = sync_pat.match(line)
        if m:
            _tally(m.group(2), _hlo_shape_bytes(m.group(1)))
    return {"ops": sum(s["ops"] for s in by_kind.values()),
            "bytes": sum(s["bytes"] for s in by_kind.values()),
            "by_kind": by_kind}


@dataclasses.dataclass
class ProgramReport:
    """XLA's cost + memory view of one compiled program.

    ``flops`` / ``bytes_accessed`` / ``transcendentals`` come from
    ``cost_analysis()``; the ``*_bytes`` fields from
    ``memory_analysis()``; ``peak_bytes`` is the derived whole-buffer
    high-water bound (see module docstring).  ``available=False`` means
    the program could not be compiled on this backend (``note`` says
    why) — a row is still emitted so the CLI shows all 40+ canonical
    programs, never a silently-shrunken registry."""

    name: str
    backend: str = ""
    available: bool = True
    note: str = ""
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    transcendentals: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    alias_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None
    peak_bytes: Optional[int] = None
    #: ISSUE 12: collective instructions / result bytes in the
    #: partitioned HLO (None when the backend exposes no HLO text;
    #: 0/0 for a genuinely collective-free single-chip program)
    collective_ops: Optional[int] = None
    collective_bytes: Optional[int] = None
    #: ISSUE 20: the launches-vs-bytes split per HLO collective kind
    #: (``{"collective-permute": {"ops": n, "bytes": b}, ...}``) — an
    #: overlap ring trades one big launch for many small ones, which
    #: only this view can tell apart from a genuine byte regression
    collective_by_kind: Optional[Dict[str, Dict[str, int]]] = None

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _derive_peak(mem: Dict[str, int]) -> Optional[int]:
    if not mem:
        return None
    have = [k for k in ("argument_bytes", "output_bytes", "temp_bytes")
            if k in mem]
    if not have:
        return None
    return (mem.get("argument_bytes", 0) + mem.get("output_bytes", 0)
            + mem.get("temp_bytes", 0) - mem.get("alias_bytes", 0))


def report_from_compiled(name: str, compiled, backend: Optional[str] = None,
                         note: str = "") -> ProgramReport:
    """Extract a :class:`ProgramReport` from a ``jax.stages.Compiled``."""
    if backend is None:
        try:
            import jax
            backend = jax.default_backend()
        except Exception:
            backend = ""
    ca = cost_analysis_dict(compiled)
    mem = memory_analysis_dict(compiled)
    coll = collective_stats(compiled)
    return ProgramReport(
        name=name, backend=backend, available=True, note=note,
        flops=(float(ca["flops"]) if "flops" in ca else None),
        bytes_accessed=(float(ca["bytes accessed"])
                        if "bytes accessed" in ca else None),
        transcendentals=(float(ca["transcendentals"])
                         if "transcendentals" in ca else None),
        argument_bytes=mem.get("argument_bytes"),
        output_bytes=mem.get("output_bytes"),
        temp_bytes=mem.get("temp_bytes"),
        alias_bytes=mem.get("alias_bytes"),
        generated_code_bytes=mem.get("generated_code_bytes"),
        peak_bytes=_derive_peak(mem),
        collective_ops=(None if coll is None else coll["ops"]),
        collective_bytes=(None if coll is None else coll["bytes"]),
        collective_by_kind=(None if coll is None else coll["by_kind"]),
    )


# ---------------------------------------------------------------------------
# canonical-registry pricing (the CLI + TPU506 share this)
# ---------------------------------------------------------------------------

def compile_program(program) -> Optional[Any]:
    """The compiled executable of a :class:`TraceProgram` — from its
    stored ``lowered`` entry, or its ``lower_thunk`` (Pallas kernel
    programs, which the registry keeps at the jaxpr level and lowers on
    demand).  None when the program carries neither.  Cached on the
    program's meta so TPU506 and the CLI never compile twice in one
    process; compile failures cache too (and re-raise) — retrying a
    deterministic failure would just double the cost of a red run."""
    cached = program.meta.get("_compiled")
    if cached is not None:
        if isinstance(cached, Exception):
            raise cached
        return cached
    lowered = getattr(program, "lowered", None)
    if lowered is None:
        thunk = getattr(program, "lower_thunk", None)
        if thunk is None:
            return None
        try:
            lowered = thunk()
        except Exception as e:
            program.meta["_compiled"] = e
            raise
    try:
        compiled = lowered.compile()
    except Exception as e:
        program.meta["_compiled"] = e
        raise
    program.meta["_compiled"] = compiled
    return compiled


def report_for_program(program) -> ProgramReport:
    """Price one canonical program; degradation per backend is a row
    with ``available=False`` and the reason, never a dropped row."""
    try:
        compiled = compile_program(program)
    except Exception as e:
        return ProgramReport(
            name=program.name, backend=_backend_name(), available=False,
            note="compile failed: %s: %s" % (type(e).__name__, e))
    if compiled is None:
        return ProgramReport(
            name=program.name, backend=_backend_name(), available=False,
            note="no lowered entry (jaxpr-only program)")
    note = ""
    if program.name.startswith("pallas/") and _backend_name() != "tpu":
        note = "interpret-mode lowering (off-chip Pallas pricing)"
    return report_from_compiled(program.name, compiled, note=note)


def _backend_name() -> str:
    try:
        import jax
        return jax.default_backend()
    except Exception:
        return ""


def registry_reports(patterns: Optional[Sequence[str]] = None
                     ) -> Tuple[List[ProgramReport], List[str], List[str]]:
    """One report per canonical-registry program (optionally
    fnmatch-filtered).  Returns ``(reports, skipped, errors)`` with the
    registry's own builder-skip/builder-error semantics — an empty
    report list must never look green (the CLI exits 2)."""
    from ..analysis.trace.programs import build_programs
    programs, skipped, errors = build_programs(patterns)
    return [report_for_program(p) for p in programs], skipped, errors


# ---------------------------------------------------------------------------
# the bench `cost` block
# ---------------------------------------------------------------------------

def cost_block(report: ProgramReport,
               step_seconds: Optional[float] = None,
               on_chip: bool = False,
               device_kind: Optional[str] = None) -> Dict[str, Any]:
    """The schema'd ``cost`` block for a bench JSON line.

    Static fields always present (None when the backend reports no
    number); ``mfu`` / ``bw_util`` derived only when ``on_chip`` and a
    positive step timing exist — CPU smoke lines carry ``null`` there
    and the trajectory gate validates shape only."""
    use_t = step_seconds if on_chip else None
    m = mfu(report.flops, use_t, device_kind)
    b = bw_util(report.bytes_accessed, use_t, device_kind)
    return {
        "flops": report.flops,
        "hbm_bytes": report.bytes_accessed,
        "peak_bytes": report.peak_bytes,
        "mfu": (round(m, 6) if m is not None else None),
        "bw_util": (round(b, 6) if b is not None else None),
    }


# ---------------------------------------------------------------------------
# CLI rendering
# ---------------------------------------------------------------------------

def _fmt_num(v: Optional[float]) -> str:
    if v is None:
        return "-"
    v = float(v)
    for unit, div in (("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(v) >= div:
            return "%.2f%s" % (v / div, unit)
    return "%.0f" % v


def format_table(reports: Sequence[ProgramReport]) -> str:
    """Human table for ``python -m paddle_tpu.observability programs``."""
    lines = ["%-42s %10s %10s %10s %10s %10s  %s"
             % ("program", "flops", "hbm_bytes", "peak", "args", "temps",
                "note")]
    for r in reports:
        lines.append("%-42s %10s %10s %10s %10s %10s  %s"
                     % (r.name, _fmt_num(r.flops),
                        _fmt_num(r.bytes_accessed), _fmt_num(r.peak_bytes),
                        _fmt_num(r.argument_bytes), _fmt_num(r.temp_bytes),
                        r.note or ("" if r.available else "UNAVAILABLE")))
    avail = sum(1 for r in reports if r.available)
    lines.append("%d program(s), %d priced (backend: %s)"
                 % (len(reports), avail, _backend_name()))
    return "\n".join(lines)
