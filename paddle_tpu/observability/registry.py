"""The process-wide metrics registry: Counter / Gauge / Histogram.

Design constraints (OBSERVABILITY.md):

* **Host-side only, never traced.**  Every ``inc``/``set``/``observe``
  converts its argument with ``float()`` up front: a jax tracer leaking in
  (someone instrumenting *inside* a jitted function) fails loudly at trace
  time instead of silently baking one stale constant into the compiled
  program.  This module imports nothing from jax.
* **Near-zero cost when disabled.**  A disabled registry hands out the
  module-level no-op singletons (:data:`NOOP_COUNTER` & co. — assertable by
  object identity), whose methods are empty: instrumented hot loops that
  fetched their handles once pay a single attribute load + no-op call per
  event and allocate nothing.  Metrics fetched while enabled keep working
  after a later ``disable()`` via one boolean attribute check.
* **Thread-safe.**  One lock per metric; snapshots lock per metric, not
  globally, so a slow exporter never stalls the serving hot path.
* **Fixed log-spaced histogram buckets.**  ``HIST_START * HIST_GROWTH**i``
  (12 buckets per decade over [1e-9, ~1e12]) — percentile readout
  (p50/p95/p99) linearly interpolates within one bucket, so relative error
  is bounded by the ~21% bucket width at any magnitude, for seconds and
  bytes alike, with no per-metric configuration and no unbounded sample
  storage.

The default registry (:func:`default_registry`) is **catalog-strict**:
every metric name must be declared in :mod:`.catalog` so dashboards never
chase undocumented names (enforced again, ops_schema-style, by
tests/test_observability.py).  Private registries (``Registry(catalog=None)``)
are free-form.

Env knobs: ``PADDLE_TPU_METRICS=0`` disables the default registry at
import; ``PADDLE_TPU_METRICS_FILE=<path>`` appends one JSONL snapshot at
interpreter exit (and on every explicit :func:`flush`).
"""
from __future__ import annotations

import math
import os
import threading
import time
from typing import Dict, Iterable, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "NoopCounter", "NoopGauge", "NoopHistogram",
    "NOOP_COUNTER", "NOOP_GAUGE", "NOOP_HISTOGRAM",
    "default_registry", "counter", "gauge", "histogram", "flush",
    "HIST_START", "HIST_GROWTH", "HIST_NBUCKETS", "bucket_bounds",
]

# -- histogram geometry (shared by every Histogram: fixed, log-spaced) ------

HIST_START = 1e-9                 # lower bound of bucket 0's upper edge
HIST_GROWTH = 10.0 ** (1.0 / 12)  # 12 buckets per decade (~21% wide)
HIST_NBUCKETS = 256               # spans ~21 decades: 1e-9 .. ~1.4e12

_LOG_GROWTH = math.log(HIST_GROWTH)


def bucket_bounds() -> Tuple[float, ...]:
    """Upper bound of each bucket (the last bucket is the +Inf overflow)."""
    return tuple(HIST_START * HIST_GROWTH ** i for i in range(HIST_NBUCKETS))


def _bucket_index(v: float) -> int:
    if v <= HIST_START:
        return 0
    i = int(math.ceil(math.log(v / HIST_START) / _LOG_GROWTH))
    return i if i < HIST_NBUCKETS else HIST_NBUCKETS - 1


def _to_float(metric, value) -> float:
    """The never-traced guard: a jax tracer has no concrete float value and
    float() on it raises at TRACE time — exactly when the bug (registry
    captured inside a compiled function) is being written."""
    try:
        return float(value)
    except Exception as e:
        raise RuntimeError(
            "metric %r observed a value with no concrete float() (%r) — "
            "metrics are host-side only and must never be recorded inside "
            "a traced/jitted function" % (metric, type(value).__name__)
        ) from e


# -- no-op fast path --------------------------------------------------------

class NoopCounter:
    """The disabled-path Counter: every method is a constant no-op."""
    __slots__ = ()

    def inc(self, n=1):
        pass

    def labels(self, **kv):
        return self

    @property
    def value(self):
        return 0.0


class NoopGauge:
    __slots__ = ()

    def set(self, v):
        pass

    def inc(self, n=1):
        pass

    def dec(self, n=1):
        pass

    def labels(self, **kv):
        return self

    @property
    def value(self):
        return 0.0


class NoopHistogram:
    __slots__ = ()

    def observe(self, v):
        pass

    def labels(self, **kv):
        return self

    def percentile(self, q):
        return 0.0

    @property
    def count(self):
        return 0

    @property
    def sum(self):
        return 0.0


#: the singletons a disabled registry hands out — instrumented code can
#: assert the fast path by identity (tests/test_observability.py does).
NOOP_COUNTER = NoopCounter()
NOOP_GAUGE = NoopGauge()
NOOP_HISTOGRAM = NoopHistogram()


# -- live metrics -----------------------------------------------------------

class _Metric:
    """Shared labeled-child machinery.  A metric created with declared
    label names is a *parent*: ``.labels(site="x")`` returns (creating on
    first use) the child keyed by the label values; unlabeled metrics are
    their own sole time series."""

    def __init__(self, name: str, registry: "Registry",
                 label_names: Tuple[str, ...] = (),
                 label_values: Tuple[str, ...] = ()):
        self.name = name
        self._registry = registry
        self._label_names = tuple(label_names)
        self._label_values = tuple(label_values)
        self._children: Dict[Tuple[str, ...], "_Metric"] = {}
        self._lock = threading.Lock()

    def labels(self, **kv):
        if set(kv) != set(self._label_names):
            raise ValueError(
                "metric %r takes labels %r, got %r"
                % (self.name, self._label_names, tuple(sorted(kv))))
        key = tuple(str(kv[k]) for k in self._label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = type(self)(self.name, self._registry,
                                   self._label_names, key)
                self._children[key] = child
        return child

    def _series(self):
        """(label_values_tuple -> leaf metric) for self + children."""
        if self._label_names and not self._label_values:
            with self._lock:
                return dict(self._children)
        return {(): self}

    @property
    def label_names(self):
        return self._label_names

    @property
    def label_values(self):
        return self._label_values

    def _reset_values(self):
        """Zero this leaf and every labeled child in place (handles stay
        live — see :meth:`Registry.reset`)."""
        self._zero()
        with self._lock:
            children = list(self._children.values())
        for c in children:
            c._reset_values()

    def _zero(self):
        raise NotImplementedError


class Counter(_Metric):
    """Monotonically increasing count (events, tokens, retries)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._value = 0.0

    def inc(self, n=1):
        if not self._registry._enabled:
            return
        n = _to_float(self.name, n)
        if n < 0:
            raise ValueError("counter %r cannot decrease" % self.name)
        with self._lock:
            self._value += n

    def _zero(self):
        with self._lock:
            self._value = 0.0

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Metric):
    """A value that goes up and down (occupancy, loss, queue depth)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._value = 0.0

    def set(self, v):
        if not self._registry._enabled:
            return
        v = _to_float(self.name, v)
        with self._lock:
            self._value = v

    def inc(self, n=1):
        if not self._registry._enabled:
            return
        n = _to_float(self.name, n)
        with self._lock:
            self._value += n

    def dec(self, n=1):
        self.inc(-_to_float(self.name, n))

    def _zero(self):
        with self._lock:
            self._value = 0.0

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Fixed log-spaced buckets + exact count/sum/min/max; p50/p95/p99 by
    in-bucket linear interpolation (error bounded by the ~21% bucket)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._buckets = [0] * HIST_NBUCKETS
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v):
        if not self._registry._enabled:
            return
        v = _to_float(self.name, v)
        i = _bucket_index(v)
        with self._lock:
            self._buckets[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    def _zero(self):
        with self._lock:
            self._buckets = [0] * HIST_NBUCKETS
            self._count = 0
            self._sum = 0.0
            self._min = math.inf
            self._max = -math.inf

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """The value at quantile ``q`` in [0, 1] (0.0 when empty)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile must be in [0, 1], got %r" % (q,))
        with self._lock:
            count = self._count
            if count == 0:
                return 0.0
            target = q * count
            seen = 0.0
            for i, n in enumerate(self._buckets):
                if n == 0:
                    continue
                if seen + n >= target:
                    if i == HIST_NBUCKETS - 1:
                        # the overflow bucket is open above: its only
                        # honest point estimate is the observed max
                        return self._max
                    lo = HIST_START * HIST_GROWTH ** (i - 1) if i else 0.0
                    hi = HIST_START * HIST_GROWTH ** i
                    frac = (target - seen) / n
                    est = lo + (hi - lo) * max(0.0, min(1.0, frac))
                    # never report outside the observed range: the first
                    # bucket is open below
                    return max(self._min, min(self._max, est))
                seen += n
            return self._max

    def snapshot_quantiles(self) -> Dict[str, float]:
        return {"p50": self.percentile(0.50),
                "p95": self.percentile(0.95),
                "p99": self.percentile(0.99)}


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}
_NOOPS = {"counter": NOOP_COUNTER, "gauge": NOOP_GAUGE,
          "histogram": NOOP_HISTOGRAM}


class Registry:
    """A named set of metrics.  ``catalog`` (a {name: spec} dict, see
    :mod:`.catalog`) makes the registry strict: undeclared names, wrong
    kinds, or undeclared label sets raise at fetch time."""

    def __init__(self, catalog: Optional[dict] = None,
                 enabled: bool = True):
        self._catalog = catalog
        self._enabled = bool(enabled)
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self):
        """Re-enable recording.  Only affects live handles (fetched while
        enabled): a fetch made while disabled returned a shared no-op
        singleton, which stays a no-op forever — that identity IS the
        zero-cost disabled path.  To instrument a component built in a
        disabled window, rebuild it (or re-fetch its handles) after
        enable()."""
        self._enabled = True

    def disable(self):
        """Subsequent fetches return the no-op singletons AND already-
        handed-out live metrics stop recording (one bool check)."""
        self._enabled = False

    def reset(self):
        """Zero every recorded value IN PLACE (benches call this after
        warmup).  The metric objects survive: components fetch their
        handles once at construction (the no-alloc hot-path contract), so
        dropping the objects would silently orphan every live handle —
        they would keep recording into metrics no exporter can see."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m._reset_values()

    # -- fetch/create ------------------------------------------------------

    def _get(self, kind: str, name: str, labels: Iterable[str] = ()):
        # catalog validation runs even when disabled: fetches happen at
        # component construction (not the hot path), and a typo'd metric
        # name should fail in a metrics-off deployment too, not only
        # explode later under metrics-on.
        labels = tuple(labels)
        if self._catalog is not None:
            spec = self._catalog.get(name)
            if spec is None:
                raise ValueError(
                    "metric %r is not declared in the observability "
                    "catalog (paddle_tpu/observability/catalog.py) — "
                    "declare it (name, type, labels, help) or use a "
                    "private Registry(catalog=None)" % name)
            if spec["type"] != kind:
                raise ValueError(
                    "metric %r is declared as a %s, fetched as a %s"
                    % (name, spec["type"], kind))
            declared = tuple(spec.get("labels", ()))
            if labels and labels != declared:
                raise ValueError(
                    "metric %r declares labels %r, fetched with %r"
                    % (name, declared, labels))
            labels = declared
        if not self._enabled:
            return _NOOPS[kind]
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = _TYPES[kind](name, self, labels)
                self._metrics[name] = m
            elif not isinstance(m, _TYPES[kind]):
                raise ValueError("metric %r already exists as %s"
                                 % (name, type(m).__name__))
        return m

    def counter(self, name: str, labels: Iterable[str] = ()) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, labels: Iterable[str] = ()) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, labels: Iterable[str] = ()) -> Histogram:
        return self._get("histogram", name, labels)

    # -- readout -----------------------------------------------------------

    def snapshot(self) -> dict:
        """One JSON-ready dict of every live series:
        ``{name: {"type", "labels": [...], "series": [{"labels": {...},
        "value"| "count"/"sum"/"min"/"max"/"p50"/"p95"/"p99"}, ...]}}``."""
        with self._lock:
            metrics = dict(self._metrics)
        out = {}
        for name, m in sorted(metrics.items()):
            kind = ("counter" if isinstance(m, Counter) else
                    "gauge" if isinstance(m, Gauge) else "histogram")
            series = []
            for values, leaf in sorted(m._series().items()):
                entry = {"labels": dict(zip(m.label_names, values))}
                if kind == "histogram":
                    with leaf._lock:
                        entry.update(count=leaf._count,
                                     sum=leaf._sum,
                                     min=(leaf._min if leaf._count else 0.0),
                                     max=(leaf._max if leaf._count else 0.0))
                    entry.update(leaf.snapshot_quantiles())
                else:
                    entry["value"] = leaf.value
                series.append(entry)
            out[name] = {"type": kind, "labels": list(m.label_names),
                         "series": series}
        return out


# -- the default (catalog-strict) registry ----------------------------------

_DEFAULT: Optional[Registry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> Registry:
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                from .catalog import CATALOG
                enabled = os.environ.get("PADDLE_TPU_METRICS", "1") not in (
                    "0", "false", "off")
                reg = Registry(catalog=CATALOG, enabled=enabled)
                _DEFAULT = reg
                if os.environ.get("PADDLE_TPU_METRICS_FILE"):
                    import atexit
                    atexit.register(flush)
    return _DEFAULT


def counter(name: str, labels: Iterable[str] = ()) -> Counter:
    return default_registry().counter(name, labels)


def gauge(name: str, labels: Iterable[str] = ()) -> Gauge:
    return default_registry().gauge(name, labels)


def histogram(name: str, labels: Iterable[str] = ()) -> Histogram:
    return default_registry().histogram(name, labels)


def flush(path: Optional[str] = None) -> Optional[str]:
    """Append one JSONL snapshot of the default registry to ``path`` (or
    ``$PADDLE_TPU_METRICS_FILE``); returns the path written, or None when
    no destination is configured."""
    path = path or os.environ.get("PADDLE_TPU_METRICS_FILE")
    if not path:
        return None
    from .exporters import JsonlExporter
    JsonlExporter(path).write(default_registry())
    return path


def now() -> float:
    """The one timestamp source exporters share (wall clock, seconds)."""
    return time.time()
