"""The black-box flight recorder: a bounded ring of recent span/engine
events plus a state snapshot, dumped to a timestamped file when the
process hits a terminal fault.

Aggregate metrics say *that* a run died; the flight recorder says what
the last N things it did were.  While active it keeps:

* a **fixed-size ring** of recent events — finished tracing spans (fed
  by :mod:`.tracing` when both are enabled), recompile-watchdog growth,
  faultpoint fires, divergence rollbacks, preemption notices — cheap
  host-side dict appends, drop-oldest;
* a registry of live :class:`~paddle_tpu.serving.engine.DecodeEngine`\\ s
  (weakrefs — recording never pins an engine) whose state summary (slot
  table, page-pool occupancy, compile counts) is collected at dump time;
* optionally, the **pre-reset cumulative metrics snapshot**: benches
  call ``Registry.reset()`` after warmup, which would zero exactly the
  counters a post-mortem wants cumulative — ``note_registry_reset()``
  (called by bench_decode.py immediately BEFORE the reset) preserves
  them as ``metrics_pre_reset`` in every later dump.

Dump triggers (wired through the PR-4 robustness hooks, so the chaos
suite can assert dump contents):

* a faultpoint action that raises (``robustness.faultpoints``),
* a strict-mode :class:`~.watchdog.RecompileError`,
* :class:`~paddle_tpu.robustness.sentinel.DivergenceError` (snapshot
  ring exhausted),
* a preemption-guard fire (``robustness.preemption``).

Each dump is one JSON file ``flight-<stamp>-<pid>-<seq>.json`` in
``PADDLE_TPU_FLIGHT_DIR`` (default: cwd) holding the trigger, the ring,
the current metrics snapshot (catalog-valid by construction — it is the
default registry's own), the pre-reset snapshot when noted, watchdog
compile counts, every live engine's state summary, and the HBM-ledger
snapshot (:func:`~paddle_tpu.observability.hbm.ledger_state` — fresh
per-device live bytes, top-arrays breakdown, KV-pool pricing: the "what
held the memory" answer an OOM post-mortem needs).

Two further trigger classes (ISSUE 14 satellites):

* **Uncaught worker-thread exceptions** — a background thread (the
  checkpoint writer, a frontend thread, any user thread) dying outside
  the typed-trigger set used to leave no black-box record.
  :func:`threading.excepthook` is chained at import: the dying thread's
  name, exception, and all-thread stacks land in a
  ``"thread_exception"`` dump before the previous hook (CPython's
  stderr print) runs.  One ``None`` check when the recorder is off.
* **Manual postmortem on signal** — ``PADDLE_TPU_FLIGHT_SIGNAL=SIGQUIT``
  (any signal name/number list) installs a handler that dumps
  all-thread stacks to stderr *from the handler frame* (faulthandler's
  C implementation: safe even when every Python lock is held) and then
  fires the ring dump from a fresh thread (kind ``"signal"``) — the
  operator's "what is this live-but-silent process doing" probe,
  without killing it.

Disabled by default (``PADDLE_TPU_FLIGHT=0`` — registry discipline):
``record()`` is one module-global ``None`` check and dump triggers
no-op, so chaos tests and production opt in via the env var or
:func:`enable`.  Dumping never raises: a broken flight dump must not
mask the fault that triggered it.
"""
from __future__ import annotations

import faulthandler
import json
import os
import signal as _signal
import sys
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from . import registry as _registry

__all__ = [
    "FlightRecorder", "enable", "disable", "active", "record",
    "register_engine", "note_registry_reset", "crash_dump",
    "last_dump_path", "RING_DEFAULT", "install_signal_handler",
    "thread_exception_dump",
]

#: default ring capacity (events); override with PADDLE_TPU_FLIGHT_RING
RING_DEFAULT = 256

#: live engines whose state summaries land in dumps; module-level (not
#: per-recorder) so engines built before enable() are still covered
_ENGINES: "weakref.WeakSet" = weakref.WeakSet()

_ACTIVE: Optional["FlightRecorder"] = None
_LOCK = threading.Lock()
_SEQ = 0


class FlightRecorder:
    def __init__(self, dir: Optional[str] = None,
                 capacity: Optional[int] = None):
        self.dir = dir or os.environ.get("PADDLE_TPU_FLIGHT_DIR") or "."
        cap = capacity if capacity is not None else int(os.environ.get(
            "PADDLE_TPU_FLIGHT_RING", RING_DEFAULT))
        self.ring: deque = deque(maxlen=max(int(cap), 1))
        # reentrant: dump() records the trigger then re-reads the ring,
        # and crash paths can re-enter record() from under a dump
        self._lock = threading.RLock()
        self._pre_reset_metrics: Optional[dict] = None
        self.dumps: List[str] = []

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, **fields):
        ev = {"kind": str(kind), "wall_ts": time.time(),
              "perf_ns": time.perf_counter_ns()}
        ev.update(fields)
        with self._lock:
            self.ring.append(ev)
        return ev

    def note_registry_reset(self, snapshot: Optional[dict] = None):
        """Preserve the cumulative metrics view a ``Registry.reset()`` is
        about to zero (call IMMEDIATELY BEFORE the reset — the ordering
        contract OBSERVABILITY.md documents)."""
        self._pre_reset_metrics = (snapshot if snapshot is not None
                                   else _registry.default_registry()
                                   .snapshot())
        self.record("registry_reset")

    # -- dumping -----------------------------------------------------------

    def _engine_states(self) -> List[dict]:
        out = []
        for e in list(_ENGINES):
            try:
                out.append(e.flight_state())
            except Exception as exc:    # a torn engine must not kill dumps
                out.append({"error": repr(exc)})
        return out

    def dump(self, trigger: Dict[str, Any],
             path: Optional[str] = None) -> str:
        """Write one flight-dump file; returns its path.  The trigger is
        recorded and the ring copied in ONE critical section, so the
        triggering event is always the dump's newest ring entry — a
        concurrent thread's record() can neither displace nor evict it."""
        try:
            metrics = _registry.default_registry().snapshot()
        except Exception:
            metrics = {}
        try:
            from .watchdog import compile_counts
            compiles = compile_counts()
        except Exception:
            compiles = {}
        # the HBM ledger snapshot (ISSUE 11): fresh per-device live
        # bytes + top-arrays breakdown + KV-pool pricing — "what held
        # the memory" for an OOM post-mortem.  ledger_state() collects
        # whether or not the ledger is armed and never raises.
        try:
            from . import hbm as _hbm
            hbm_state = _hbm.ledger_state()
        except Exception as e:
            hbm_state = {"error": repr(e)}
        with self._lock:    # RLock: record() below re-enters it
            self.record("trigger", detail=dict(trigger))
            ring = list(self.ring)
            pre = self._pre_reset_metrics
        doc = {
            "format": "paddle_tpu-flight-v1",
            "wall_ts": time.time(),
            "perf_ns": time.perf_counter_ns(),
            "pid": os.getpid(),
            "trigger": dict(trigger),
            "ring": ring,
            "ring_capacity": self.ring.maxlen,
            "metrics": metrics,
            "metrics_pre_reset": pre,
            "compile_counts": compiles,
            "engines": self._engine_states(),
            "hbm": hbm_state,
        }
        if path is None:
            global _SEQ
            with _LOCK:
                _SEQ += 1
                seq = _SEQ
            os.makedirs(self.dir, exist_ok=True)
            path = os.path.join(
                self.dir, "flight-%s-%d-%d.json"
                % (time.strftime("%Y%m%dT%H%M%S"), os.getpid(), seq))
        with open(path, "w") as f:
            json.dump(doc, f, sort_keys=True)
            f.write("\n")
        self.dumps.append(path)
        return path


# ---------------------------------------------------------------------------
# module-level API (what the instrumented subsystems call)
# ---------------------------------------------------------------------------

def enable(dir: Optional[str] = None,
           capacity: Optional[int] = None) -> FlightRecorder:
    """Install (or replace) the process-wide recorder."""
    global _ACTIVE
    with _LOCK:
        _ACTIVE = FlightRecorder(dir=dir, capacity=capacity)
        return _ACTIVE


def disable():
    global _ACTIVE
    with _LOCK:
        _ACTIVE = None


def active() -> Optional[FlightRecorder]:
    return _ACTIVE


def record(kind: str, **fields):
    """Ring append when a recorder is active; one global ``None`` check
    otherwise (cheap enough for the instrumented fault paths)."""
    r = _ACTIVE
    if r is None:
        return None
    return r.record(kind, **fields)


def register_engine(engine):
    """Track a serving engine (weakref) for dump-time state summaries.
    Always cheap; engines register unconditionally at construction."""
    _ENGINES.add(engine)


def note_registry_reset(snapshot: Optional[dict] = None):
    r = _ACTIVE
    if r is None:
        return None
    return r.note_registry_reset(snapshot)


def crash_dump(trigger: Dict[str, Any]) -> Optional[str]:
    """Dump on a terminal fault; never raises (a failed dump must not
    mask the fault being reported).  Returns the path or None."""
    r = _ACTIVE
    if r is None:
        return None
    try:
        path = r.dump(trigger)
        sys.stderr.write("[flight] dumped %s (trigger: %s)\n"
                         % (path, trigger.get("kind")))
        return path
    except Exception as e:
        sys.stderr.write("[flight] dump FAILED: %r\n" % (e,))
        return None


def last_dump_path() -> Optional[str]:
    r = _ACTIVE
    if r is None or not r.dumps:
        return None
    return r.dumps[-1]


# ---------------------------------------------------------------------------
# uncaught worker-thread exceptions (ISSUE 14 satellite)
# ---------------------------------------------------------------------------

def _all_thread_stacks() -> str:
    from .liveness import all_thread_stacks
    return all_thread_stacks()


_PREV_THREAD_EXCEPTHOOK = None


def thread_exception_dump(thread_name: str, exc: BaseException,
                          tb=None) -> Optional[str]:
    """One ``"thread_exception"`` flight dump for a dying worker thread
    (the excepthook below and any component that catches its own
    thread's death — the serving frontend — share this, so the dump
    shape cannot drift).  One ``None`` check when the recorder is
    disarmed: the stack collection is never paid for nothing.  Never
    raises."""
    if _ACTIVE is None:
        return None
    try:
        import traceback as _tb
        tb_text = "".join(_tb.format_exception(
            type(exc), exc, exc.__traceback__ if tb is None else tb))
        record("thread_exception", thread=thread_name, error=repr(exc))
        # "traceback" is the dying thread's unwound frames; "stacks" is
        # every OTHER thread at death time (a hook runs on the dying
        # thread, whose live frames are the hook's own)
        return crash_dump({"kind": "thread_exception",
                           "thread": thread_name, "error": repr(exc),
                           "traceback": tb_text,
                           "stacks": _all_thread_stacks()})
    except Exception:
        return None   # never mask the thread's own traceback print


def _thread_excepthook(args):
    """Chained :func:`threading.excepthook`: a worker thread dying on an
    uncaught exception gets a black-box record BEFORE the interpreter's
    default stderr print — today that death is otherwise invisible to
    every postmortem (the typed triggers only cover faults the hardened
    code anticipated).  SystemExit is a normal thread exit, not a
    fault."""
    if args.exc_type is not SystemExit and args.exc_value is not None:
        name = args.thread.name if args.thread is not None else "?"
        thread_exception_dump(name, args.exc_value,
                              tb=args.exc_traceback)
    _PREV_THREAD_EXCEPTHOOK(args)


def _install_thread_excepthook():
    global _PREV_THREAD_EXCEPTHOOK
    if _PREV_THREAD_EXCEPTHOOK is None:
        _PREV_THREAD_EXCEPTHOOK = threading.excepthook
        threading.excepthook = _thread_excepthook


_install_thread_excepthook()


# ---------------------------------------------------------------------------
# manual postmortem trigger (ISSUE 14 satellite): PADDLE_TPU_FLIGHT_SIGNAL
# ---------------------------------------------------------------------------

def _on_flight_signal(signum, frame):
    # the Python half of the postmortem: the all-thread stderr stacks
    # already fired from faulthandler's C-LEVEL handler (registered
    # with chain=True below — it runs even while the main thread is
    # wedged inside native code, the motivating hang; THIS handler only
    # runs at the next bytecode boundary).  Here we add the ring dump,
    # on a FRESH thread: it needs Python locks and file IO, and if the
    # process is wedged on a lock the C stacks still landed, which is
    # the postmortem that matters.
    name = _signal.Signals(signum).name
    try:
        sys.stderr.write("[flight] %s received — all-thread stacks "
                         "dumped; writing the flight ring\n" % name)
        sys.stderr.flush()
    except Exception:
        pass

    def _dump():
        stacks = _all_thread_stacks()
        record("signal", signal=name)
        crash_dump({"kind": "signal", "signal": name, "stacks": stacks})

    threading.Thread(target=_dump, name="flight-signal-dump",
                     daemon=True).start()


def install_signal_handler(spec: Optional[str] = None) -> List[str]:
    """Install the manual-postmortem handler for every signal named in
    ``spec`` (or ``$PADDLE_TPU_FLIGHT_SIGNAL``): comma-separated names
    or numbers, e.g. ``SIGQUIT``.  Two layers per signal: a
    ``faulthandler.register(..., chain=True)`` C-level handler (the
    all-thread stack dump — fires even when the main thread is blocked
    inside a native call, where a Python-level handler can never run)
    chained onto a Python handler that adds the flight ring dump when
    the interpreter next reaches a bytecode boundary.  Returns the
    names installed; no-op (empty list) when unset or not on the main
    thread."""
    spec = spec if spec is not None else os.environ.get(
        "PADDLE_TPU_FLIGHT_SIGNAL", "")
    installed = []
    for tok in (t.strip() for t in spec.split(",")):
        if not tok:
            continue
        if tok.isdigit():
            sig = _signal.Signals(int(tok))
        elif hasattr(_signal, tok):
            sig = getattr(_signal, tok)
        else:
            raise ValueError(
                "PADDLE_TPU_FLIGHT_SIGNAL: unknown signal %r" % tok)
        try:
            # Python handler FIRST, then the C handler chains to it:
            # stacks dump immediately in C, the ring dump follows when
            # (if) the main thread returns to Python
            _signal.signal(sig, _on_flight_signal)
            faulthandler.register(sig, all_threads=True, chain=True)
        except (ValueError, OSError, RuntimeError, AttributeError):
            # not the main thread, an uncatchable signal (SIGKILL), or
            # a platform without register(): skip, never crash
            continue
        installed.append(sig.name)
    return installed


# import-time install degrades LOUDLY, never fatally: a typo'd value in
# an optional postmortem knob must not make `import paddle_tpu` itself
# crash every job that never wanted the handler
try:
    install_signal_handler()
except (ValueError, OSError) as _e:
    sys.stderr.write("[flight] PADDLE_TPU_FLIGHT_SIGNAL ignored: %s\n"
                     % (_e,))


# env opt-in: PADDLE_TPU_FLIGHT=1 arms the recorder at import time (the
# registry's env-knob discipline; PADDLE_TPU_FLIGHT_DIR/_RING configure it)
if os.environ.get("PADDLE_TPU_FLIGHT", "0") not in ("0", "", "false",
                                                    "off"):
    enable()
