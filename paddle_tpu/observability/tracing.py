"""Request-scoped span tracing — the per-request layer the aggregate
metrics registry cannot express.

PR 6 gave the serving engine p50/p99 histograms; after the paged cache
(PR 7) and speculative decode (PR 8) a single request's lifecycle —
queue wait, chunked prefill interleaved with decode, prefix-cache hits,
copy-on-write, verify accept/reject runs, recompute preemption and
re-admission — is not reconstructable from any of them: a p99 TTFT
outlier is unattributable to its cause.  This module is the cheap
host-side span API the scheduler/engine thread a ``trace_id`` through:

* a **trace** is one request's lane, minted at ``submit()``
  (:meth:`Tracer.new_trace`); ``trace_id 0`` is the shared engine lane
  (compiled-entry dispatch spans, page-allocator events);
* a **span** has a name, parent link, monotonic ``perf_counter_ns``
  timestamps (the SAME clock the profiler's ``RecordEvent`` uses, so a
  chrome-trace export of both is time-aligned in one Perfetto load),
  structured attrs, and point-in-time **events** (prefix-hit, CoW,
  preempted, first-token);
* exports: JSONL (one span per line, via the same append/atexit
  discipline as the metrics ``flush()``) and chrome-trace JSON (request
  lanes as named threads, span events as instants, optionally merged
  with the live profiler's host spans + metric marks).

Discipline (same as the registry):

* **Disabled by default** (``PADDLE_TPU_TRACING=0``): the default
  tracer is the module-level :data:`NOOP_TRACER` — every ``span()``
  returns the shared :data:`NOOP_SPAN` by identity, so instrumented hot
  loops pay one attribute load and an empty method call (asserted by
  tests/test_tracing.py, PR-6 style).
* **Host-side only, never traced.**  Every span attr value is checked
  with ``float()`` up front: a jax tracer leaking in (someone tracing
  *inside* a jitted function) raises at TRACE time instead of baking a
  stale constant into a compiled program.  This module imports nothing
  from jax.
* **Bounded.**  The span buffer is capped (``PADDLE_TPU_TRACE_CAP``);
  overflow drops oldest-first and counts the drops — tracing a
  multi-hour serving run degrades to a tail window, never to OOM.

The analyzer half (:func:`build_report` / ``python -m
paddle_tpu.observability trace-report``) reconstructs per-request
timelines from a trace file and attributes TTFT/TPOT across queue vs
prefill vs decode vs preemption-rework — cross-checked in tests against
the PR-6 histograms on the same run.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import flight as _flight

__all__ = [
    "Span", "NoopSpan", "Tracer", "NoopTracer",
    "NOOP_SPAN", "NOOP_TRACER",
    "default_tracer", "load_trace", "build_report", "format_report",
    "build_sli", "format_sli", "chrome_events", "write_chrome",
]

#: default bound on buffered spans+events per tracer (drop-oldest past it)
TRACE_CAP_DEFAULT = 200_000

#: the engine lane: spans/events that belong to the shared engine (one
#: compiled step serves every request), not to any single request's trace
ENGINE_LANE = 0


def _attr_value(name: str, v: Any):
    """The never-traced guard (registry ``_to_float`` discipline): span
    attrs must be plain host values — a jax tracer has no concrete
    ``float()`` and raises here, at trace time, where the bug (tracing
    captured inside a compiled function) is being written."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    try:
        return float(v)
    except Exception as e:
        raise RuntimeError(
            "span attr %r got a value with no concrete float() (%r) — "
            "tracing is host-side only and must never run inside a "
            "traced/jitted function" % (name, type(v).__name__)) from e


def _attrs(kv: Dict[str, Any]) -> Dict[str, Any]:
    return {k: _attr_value(k, v) for k, v in kv.items()}


class Span:
    """One timed operation in a request's lane.  Created started; call
    :meth:`end` (or use as a context manager) to close it.  ``event()``
    attaches a timestamped point event (prefix-hit, preempted, ...)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_ns",
                 "end_ns", "attrs", "events", "_tracer")

    def __init__(self, tracer, name, trace_id, span_id, parent_id,
                 start_ns, attrs):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns = None
        self.attrs = attrs
        self.events: List[Dict[str, Any]] = []

    def set_attr(self, **kv):
        self.attrs.update(_attrs(kv))
        return self

    def event(self, name: str, **attrs):
        self.events.append({"name": name,
                            "ts_ns": time.perf_counter_ns(),
                            "attrs": _attrs(attrs)})
        return self

    def end(self, end_ns: Optional[int] = None, **attrs):
        if self.end_ns is not None:    # idempotent: first end wins
            return self
        if attrs:
            self.attrs.update(_attrs(attrs))
        self.end_ns = int(end_ns if end_ns is not None
                          else time.perf_counter_ns())
        self._tracer._on_end(self)
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": "span", "name": self.name,
                "trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "start_ns": self.start_ns,
                "end_ns": self.end_ns, "attrs": self.attrs,
                "events": self.events}


class NoopSpan:
    """The disabled-path span: every method is a constant no-op returning
    self (so chained/context-manager use costs nothing)."""

    __slots__ = ()
    name = ""
    trace_id = 0
    span_id = 0
    parent_id = None
    start_ns = 0
    end_ns = 0
    attrs: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []

    def set_attr(self, **kv):
        return self

    def event(self, name, **attrs):
        return self

    def end(self, end_ns=None, **attrs):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


#: the singleton a disabled tracer hands out — instrumented code can
#: assert the fast path by identity (tests/test_tracing.py does).
NOOP_SPAN = NoopSpan()


class Tracer:
    """A live span collector.  Thread-safe; bounded (drop-oldest)."""

    enabled = True

    def __init__(self, capacity: Optional[int] = None):
        cap = capacity if capacity is not None else int(os.environ.get(
            "PADDLE_TPU_TRACE_CAP", TRACE_CAP_DEFAULT))
        self._cap = max(int(cap), 1)
        self._lock = threading.Lock()
        # deques: drop-oldest past the cap stays O(1) per append — a
        # list.pop(0) here would turn every hot-loop span O(cap) once a
        # long run fills the buffer
        self._spans: "deque[Span]" = deque()
        self._events: "deque[Dict[str, Any]]" = deque()  # instants
        self._next_trace = 0
        self._next_span = 0
        self.dropped = 0
        # perf_counter_ns <-> wall-clock anchor for cross-file alignment
        self._anchor = {"wall_ts": time.time(),
                        "perf_ns": time.perf_counter_ns()}

    # -- minting -----------------------------------------------------------

    def new_trace(self) -> int:
        """Mint a request lane id (> 0; 0 is the engine lane)."""
        with self._lock:
            self._next_trace += 1
            return self._next_trace

    def _new_span_id(self) -> int:
        with self._lock:
            self._next_span += 1
            return self._next_span

    # -- recording ---------------------------------------------------------

    def span(self, name: str, trace_id: Optional[int] = None,
             parent: Optional[Span] = None, **attrs) -> Span:
        """Open a span (started now).  ``parent`` links it into a trace
        tree and supplies the ``trace_id`` when not given explicitly."""
        if trace_id is None:
            trace_id = parent.trace_id if parent is not None else ENGINE_LANE
        s = Span(self, name, int(trace_id), self._new_span_id(),
                 parent.span_id if parent is not None else None,
                 time.perf_counter_ns(), _attrs(attrs))
        self._append(self._spans, s)
        return s

    def add_span(self, name: str, start_ns: int, end_ns: int,
                 trace_id: Optional[int] = None,
                 parent: Optional[Span] = None, **attrs) -> Span:
        """Record an already-timed span (closed-interval constructor —
        the decode hot loop measures once and stamps every involved
        request's span with the same interval)."""
        if trace_id is None:
            trace_id = parent.trace_id if parent is not None else ENGINE_LANE
        s = Span(self, name, int(trace_id), self._new_span_id(),
                 parent.span_id if parent is not None else None,
                 int(start_ns), _attrs(attrs))
        self._append(self._spans, s)
        s.end(end_ns=int(end_ns))
        return s

    def instant(self, name: str, trace_id: int = ENGINE_LANE, **attrs):
        """A standalone point event (page reclaim, CoW remap, ...) on a
        lane, not attached to any span."""
        self._append(self._events, {
            "kind": "event", "name": name, "trace_id": int(trace_id),
            "ts_ns": time.perf_counter_ns(), "attrs": _attrs(attrs)})

    def _append(self, buf, item):
        with self._lock:
            buf.append(item)
            if len(self._spans) + len(self._events) > self._cap:
                # true drop-OLDEST across both buffers: evicting spans
                # whenever any exist would let accumulated instants
                # squeeze the span window to nothing on long runs
                if not self._events:
                    victim = self._spans
                elif not self._spans:
                    victim = self._events
                else:
                    victim = (self._spans
                              if self._spans[0].start_ns
                              <= self._events[0]["ts_ns"]
                              else self._events)
                victim.popleft()
                self.dropped += 1

    def _on_end(self, span: Span):
        # feed the flight recorder's ring (one global None-check when the
        # recorder is inactive)
        if _flight.active() is not None:
            _flight.record("span", name=span.name, trace_id=span.trace_id,
                           span_id=span.span_id,
                           dur_ns=(span.end_ns or span.start_ns)
                           - span.start_ns, attrs=dict(span.attrs))

    # -- readout -----------------------------------------------------------

    @property
    def span_count(self) -> int:
        with self._lock:
            return len(self._spans)

    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            spans = list(self._spans)
        return [s.to_dict() for s in spans]

    def instants(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events]

    def span_counts(self) -> Dict[int, int]:
        """{trace_id: spans recorded} — the bench's per-request counts."""
        out: Dict[int, int] = {}
        with self._lock:
            for s in self._spans:
                out[s.trace_id] = out.get(s.trace_id, 0) + 1
        return out

    def reset(self):
        """Drop recorded spans/events (the bench does this after warmup
        so the exported trace describes the timed drain only).  Trace and
        span id counters keep advancing — ids never repeat."""
        with self._lock:
            self._spans.clear()
            self._events.clear()
            self.dropped = 0
            self._anchor = {"wall_ts": time.time(),
                            "perf_ns": time.perf_counter_ns()}

    # -- export ------------------------------------------------------------

    def export_jsonl(self, path: str, mode: str = "w") -> str:
        """Write the trace as JSONL: one meta line (the wall-clock anchor
        for ``perf_counter_ns`` timestamps), then one line per span and
        per instant event."""
        with self._lock:
            spans = [s.to_dict() for s in self._spans]
            events = [dict(e) for e in self._events]
            meta = {"kind": "meta", "format": "paddle_tpu-trace-v1",
                    "pid": os.getpid(), "dropped": self.dropped,
                    **self._anchor}
        with open(path, mode) as f:
            for doc in [meta] + spans + events:
                f.write(json.dumps(doc, sort_keys=True) + "\n")
        return path

    def export_chrome(self, path: str, include_profiler: bool = True
                      ) -> str:
        """Write a chrome://tracing JSON of this tracer's spans (request
        lanes as named threads); ``include_profiler=True`` merges a COPY
        of the live profiler's host spans and metric marks (same
        ``perf_counter_ns`` clock, so everything is time-aligned)."""
        return write_chrome(path, self.spans(), self.instants(),
                            include_profiler=include_profiler)

    def flush(self, path: Optional[str] = None) -> Optional[str]:
        """Append-export to ``path`` or ``$PADDLE_TPU_TRACE_FILE`` (the
        atexit hook of the default tracer); None when unconfigured."""
        path = path or os.environ.get("PADDLE_TPU_TRACE_FILE")
        if not path:
            return None
        return self.export_jsonl(path, mode="a")


class NoopTracer:
    """The disabled default tracer: identity no-ops everywhere."""

    enabled = False
    dropped = 0
    span_count = 0

    def new_trace(self) -> int:
        return 0

    def span(self, name, trace_id=None, parent=None, **attrs):
        return NOOP_SPAN

    def add_span(self, name, start_ns, end_ns, trace_id=None, parent=None,
                 **attrs):
        return NOOP_SPAN

    def instant(self, name, trace_id=ENGINE_LANE, **attrs):
        pass

    def spans(self):
        return []

    def instants(self):
        return []

    def span_counts(self):
        return {}

    def reset(self):
        pass

    def export_jsonl(self, path, mode="w"):
        raise RuntimeError(
            "tracing is disabled (PADDLE_TPU_TRACING=0) — nothing to "
            "export; enable it or pass a live Tracer to the engine/"
            "scheduler")

    def export_chrome(self, path, include_profiler=True):
        # own def (not an alias): the kwargs must match the live
        # signature so callers get the explanatory error, not TypeError
        self.export_jsonl(path)

    def flush(self, path=None):
        return None


#: the singleton :func:`default_tracer` returns while disabled —
#: assertable by identity, PR-6 style.
NOOP_TRACER = NoopTracer()


_DEFAULT: Optional[Tracer] = None
_DEFAULT_LOCK = threading.Lock()


def default_tracer():
    """The process-wide tracer.  Disabled (the default,
    ``PADDLE_TPU_TRACING`` unset/0) it is :data:`NOOP_TRACER` by
    identity; enabled (``PADDLE_TPU_TRACING=1``) it is one live
    :class:`Tracer`, with an atexit JSONL flush when
    ``PADDLE_TPU_TRACE_FILE`` is set.  Like the registry, the decision
    is made once: components fetch their tracer at construction."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                on = os.environ.get("PADDLE_TPU_TRACING", "0") not in (
                    "0", "", "false", "off")
                if not on:
                    _DEFAULT = NOOP_TRACER
                else:
                    _DEFAULT = Tracer()
                    if os.environ.get("PADDLE_TPU_TRACE_FILE"):
                        import atexit
                        atexit.register(_DEFAULT.flush)
    return _DEFAULT


# ---------------------------------------------------------------------------
# chrome-trace export
# ---------------------------------------------------------------------------

def chrome_events(spans: Iterable[Dict[str, Any]],
                  events: Iterable[Dict[str, Any]] = (),
                  pid: Optional[int] = None) -> List[Dict[str, Any]]:
    """Chrome-trace event list for span/event dicts.  Each trace lane is
    a named synthetic thread (``request <id>``; lane 0 is ``engine``),
    so Perfetto renders one swimlane per request; span events and
    standalone instants become thread-scoped ``"i"`` events."""
    pid = os.getpid() if pid is None else pid
    out: List[Dict[str, Any]] = []
    lanes = set()

    def lane(tid):
        if tid not in lanes:
            lanes.add(tid)
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid,
                        "args": {"name": ("engine" if tid == ENGINE_LANE
                                          else "request %d" % tid)}})
            out.append({"name": "thread_sort_index", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"sort_index": tid}})
        return tid

    for s in spans:
        tid = lane(int(s["trace_id"]))
        end = s["end_ns"] if s["end_ns"] is not None else s["start_ns"]
        out.append({"name": s["name"], "ph": "X", "pid": pid, "tid": tid,
                    "ts": s["start_ns"] / 1000.0,
                    "dur": max(end - s["start_ns"], 0) / 1000.0,
                    "cat": "request" if tid != ENGINE_LANE else "engine",
                    "args": dict(s.get("attrs") or {})})
        for ev in s.get("events") or ():
            out.append({"name": ev["name"], "ph": "i", "s": "t",
                        "pid": pid, "tid": tid,
                        "ts": ev["ts_ns"] / 1000.0, "cat": "event",
                        "args": dict(ev.get("attrs") or {})})
    for ev in events:
        tid = lane(int(ev.get("trace_id", ENGINE_LANE)))
        out.append({"name": ev["name"], "ph": "i", "s": "t", "pid": pid,
                    "tid": tid, "ts": ev["ts_ns"] / 1000.0, "cat": "event",
                    "args": dict(ev.get("attrs") or {})})
    return out


def write_chrome(path: str, spans, events=(), include_profiler=True
                 ) -> str:
    """Write chrome://tracing JSON.  ``include_profiler=True`` copies
    (never drains — a live Profiler still owns its stream) the host
    profiler's RecordEvent spans and metric marks into the same file;
    both use ``perf_counter_ns``, so Perfetto shows device spans,
    counters, and request lanes on one timeline."""
    all_events = chrome_events(spans, events)
    if include_profiler:
        try:    # lazy: the profiler package imports jax at module load
            from .. import profiler as _prof
        except ImportError:
            _prof = None    # jax-less process: spans-only export
        if _prof is not None:
            # narrow on purpose: only the jax-less import is tolerated —
            # drift in the profiler internals must surface, not silently
            # drop device spans/marks from every export
            with _prof._recorder._lock:
                host = list(_prof._recorder._events)
            pid = os.getpid()
            all_events.extend({
                "name": name, "ph": "X", "ts": ts / 1000.0,
                "dur": dur / 1000.0, "pid": pid, "tid": tid, "cat": "host",
            } for name, ts, dur, tid in host)
            all_events.extend({
                "name": name, "ph": "C", "ts": ts / 1000.0, "pid": pid,
                "cat": "metric", "args": {"value": value},
            } for name, ts, value in list(_prof._metric_marks))
    # HBM-ledger counter lanes (ISSUE 11): occupancy samples share the
    # perf_counter_ns clock, so Perfetto shows live/KV-pool bytes
    # time-aligned with the request lanes.  [] while the ledger is
    # disarmed; hbm imports no jax at module level (tracing discipline).
    from . import hbm as _hbm
    all_events.extend({
        "name": name, "ph": "C", "ts": ts / 1000.0, "pid": os.getpid(),
        "cat": "hbm", "args": {"value": value},
    } for name, ts, value in _hbm.counter_marks())
    with open(path, "w") as f:
        json.dump({"traceEvents": all_events}, f)
    return path


# ---------------------------------------------------------------------------
# trace file loading + per-request reconstruction (the analyzer)
# ---------------------------------------------------------------------------

def load_trace(path: str) -> Tuple[List[dict], List[dict], List[dict]]:
    """(spans, events, metas) from a JSONL trace file; malformed lines
    are skipped (a torn tail from a crashed writer must not kill the
    post-mortem that needs it most).

    Appended multi-run files (the atexit ``flush(mode="a")`` path) are
    handled: every ``meta`` line starts a new run segment, and each
    segment's trace/span ids — which restart at 1 in every process —
    are renumbered into one shared namespace, so two runs' requests can
    never merge into one trace or alias span ids across runs.  Each
    returned span/event carries its 0-based ``run`` index."""
    spans, events, metas = [], [], []
    run = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            kind = doc.get("kind")
            if kind == "meta":
                if spans or events or metas:
                    run += 1
                metas.append(doc)
            elif kind == "span":
                doc["run"] = run
                spans.append(doc)
            elif kind == "event":
                doc["run"] = run
                events.append(doc)
    if run:    # multi-run file: renumber ids into one namespace
        trace_map: Dict[Tuple[int, int], int] = {}
        span_map: Dict[Tuple[int, int], int] = {}

        def tid_for(r, tid):
            if tid == ENGINE_LANE:    # the engine lane is shared
                return ENGINE_LANE
            return trace_map.setdefault((r, tid), len(trace_map) + 1)

        def sid_for(r, sid):
            return span_map.setdefault((r, sid), len(span_map) + 1)

        for s in spans:
            s["trace_id"] = tid_for(s["run"], s["trace_id"])
            s["span_id"] = sid_for(s["run"], s["span_id"])
            if s.get("parent_id") is not None:
                s["parent_id"] = sid_for(s["run"], s["parent_id"])
        for e in events:
            e["trace_id"] = tid_for(e["run"], e["trace_id"])
    return spans, events, metas


_PREFILL_NAMES = ("prefill", "prefill_chunk")
_DECODE_NAMES = ("decode", "spec_verify")


def build_report(spans: List[dict], events: List[dict] = ()) -> dict:
    """Reconstruct per-request timelines from span dicts.

    For every trace with a ``request`` root span: verify the span tree
    is CONNECTED (every span of the trace reaches the root via parent
    links), recover TTFT (root start -> ``first_token`` event) and TPOT
    (decode time / decode-committed tokens — the scheduler's own
    definition), and attribute the request's wall time across **queue**
    (initial admission wait) / **prefill** (first-admission chunks) /
    **decode** (decode + spec-verify iterations) / **rework**
    (preemption requeue wait + recompute-prefill chunks)."""
    by_trace: Dict[int, List[dict]] = {}
    for s in spans:
        by_trace.setdefault(int(s["trace_id"]), []).append(s)

    requests = []
    for tid, group in sorted(by_trace.items()):
        roots = [s for s in group if s["name"] == "request"]
        if tid == ENGINE_LANE or not roots:
            continue
        root = roots[0]
        by_id = {s["span_id"]: s for s in group}
        # connectivity: walk parents up to the root
        connected = True
        for s in group:
            seen, cur = set(), s
            while cur is not None and cur["span_id"] != root["span_id"]:
                if cur["span_id"] in seen:       # cycle: broken trace
                    cur = None
                    break
                seen.add(cur["span_id"])
                cur = by_id.get(cur["parent_id"])
            if cur is None:
                connected = False

        def dur(s):
            end = s["end_ns"] if s["end_ns"] is not None else s["start_ns"]
            return (end - s["start_ns"]) * 1e-9

        queue_s = sum(dur(s) for s in group if s["name"] == "queue")
        rework_wait_s = sum(dur(s) for s in group
                            if s["name"] == "requeue")
        prefill_s = rework_prefill_s = 0.0
        for s in group:
            if s["name"] in _PREFILL_NAMES:
                if (s.get("attrs") or {}).get("rework"):
                    rework_prefill_s += dur(s)
                else:
                    prefill_s += dur(s)
        decode_s = decode_tokens = 0
        spec_iters = 0
        for s in group:
            if s["name"] in _DECODE_NAMES:
                decode_s += dur(s)
                decode_tokens += int((s.get("attrs") or {}
                                      ).get("tokens", 0))
                if s["name"] == "spec_verify":
                    spec_iters += 1
        root_events = [e for s in group for e in (s.get("events") or ())]
        first_tok = [e for e in root_events if e["name"] == "first_token"]
        ttft_s = ((min(e["ts_ns"] for e in first_tok)
                   - root["start_ns"]) * 1e-9) if first_tok else None
        prefix_hits = [e for e in root_events if e["name"] == "prefix_hit"]
        preemptions = sum(1 for e in root_events
                          if e["name"] == "preempted")
        rework_s = rework_wait_s + rework_prefill_s
        total = queue_s + prefill_s + decode_s + rework_s
        attribution = {k: (v / total if total > 0 else 0.0)
                       for k, v in (("queue", queue_s),
                                    ("prefill", prefill_s),
                                    ("decode", decode_s),
                                    ("rework", rework_s))}
        attrs = root.get("attrs") or {}
        requests.append({
            "trace_id": tid,
            "rid": attrs.get("rid"),
            "finish_reason": attrs.get("reason"),
            "spans": len(group),
            "connected": connected,
            "ttft_s": ttft_s,
            "tpot_s": (decode_s / decode_tokens) if decode_tokens else 0.0,
            "queue_s": queue_s,
            "prefill_s": prefill_s,
            "decode_s": decode_s,
            "decode_tokens": decode_tokens,
            "spec_verify_iterations": spec_iters,
            "rework_s": rework_s,
            "rework_wait_s": rework_wait_s,
            "rework_prefill_s": rework_prefill_s,
            "prefix_hit_tokens": sum(int(e["attrs"].get("tokens", 0))
                                     for e in prefix_hits),
            "preemptions": preemptions,
            "attribution": attribution,
        })

    with_ttft = [r for r in requests if r["ttft_s"] is not None]
    # standalone instants (pages.prefix_share / cow_remap / reclaim)
    # summarized by name — the page-lifecycle side of the timeline
    instants: Dict[str, int] = {}
    for e in events:
        instants[e["name"]] = instants.get(e["name"], 0) + 1
    totals = {
        "requests": len(requests),
        "spans": sum(len(g) for t, g in by_trace.items()
                     if t != ENGINE_LANE),
        "engine_spans": len(by_trace.get(ENGINE_LANE, [])),
        "instants": instants,
        "connected": all(r["connected"] for r in requests),
        "ttft_sum_s": sum(r["ttft_s"] for r in with_ttft),
        "ttft_count": len(with_ttft),
        "tpot_mean_s": (sum(r["tpot_s"] for r in requests
                            if r["decode_tokens"])
                        / max(sum(1 for r in requests
                                  if r["decode_tokens"]), 1)),
        "decode_tokens": sum(r["decode_tokens"] for r in requests),
        "preemptions": sum(r["preemptions"] for r in requests),
    }
    return {"requests": requests, "totals": totals}


def _pct(sorted_vals: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile over exact per-request values (the SLI
    table's statistic — not the registry histogram's bucketed
    interpolation, which it is cross-checked against in tests)."""
    if not sorted_vals:
        return None
    idx = max(int(-(-q * len(sorted_vals) // 1)) - 1, 0)
    return sorted_vals[min(idx, len(sorted_vals) - 1)]


def build_sli(report: dict) -> Dict[str, Dict[str, Any]]:
    """Per-finish-reason SLI rollup from a :func:`build_report` result:
    request count plus p50/p99 TTFT and TPOT (seconds; ``None`` when no
    request of that reason carries the statistic — a mid-prefill
    eviction has no TTFT, PR-7 discipline)."""
    by_reason: Dict[str, List[dict]] = {}
    for r in report["requests"]:
        by_reason.setdefault(str(r["finish_reason"] or "unknown"),
                             []).append(r)
    out: Dict[str, Dict[str, Any]] = {}
    for reason, rs in sorted(by_reason.items()):
        ttfts = sorted(r["ttft_s"] for r in rs if r["ttft_s"] is not None)
        tpots = sorted(r["tpot_s"] for r in rs if r["decode_tokens"])
        out[reason] = {
            "requests": len(rs),
            "ttft_p50_s": _pct(ttfts, 0.50), "ttft_p99_s": _pct(ttfts, 0.99),
            "tpot_p50_s": _pct(tpots, 0.50), "tpot_p99_s": _pct(tpots, 0.99),
        }
    return out


def format_sli(sli: Dict[str, Dict[str, Any]]) -> str:
    """Human table for ``trace-report --sli``."""
    lines = ["%-16s %8s %12s %12s %12s %12s"
             % ("finish_reason", "requests", "ttft_p50_ms", "ttft_p99_ms",
                "tpot_p50_ms", "tpot_p99_ms")]

    def ms(v):
        return "%.3f" % (1e3 * v) if v is not None else "-"

    for reason, row in sli.items():
        lines.append("%-16s %8d %12s %12s %12s %12s"
                     % (reason, row["requests"], ms(row["ttft_p50_s"]),
                        ms(row["ttft_p99_s"]), ms(row["tpot_p50_s"]),
                        ms(row["tpot_p99_s"])))
    return "\n".join(lines)


def format_report(report: dict) -> str:
    """Human table for the ``trace-report`` CLI."""
    lines = ["%-4s %-5s %-6s %-9s %-9s %-24s %s"
             % ("rid", "trace", "spans", "ttft_ms", "tpot_ms",
                "queue/prefill/decode/rework", "notes")]
    for r in report["requests"]:
        att = r["attribution"]
        shares = "/".join("%.0f%%" % (100 * att[k])
                          for k in ("queue", "prefill", "decode", "rework"))
        notes = []
        if not r["connected"]:
            notes.append("DISCONNECTED")
        if r["prefix_hit_tokens"]:
            notes.append("prefix_hit=%d" % r["prefix_hit_tokens"])
        if r["preemptions"]:
            notes.append("preempted=%d" % r["preemptions"])
        if r["spec_verify_iterations"]:
            notes.append("spec_iters=%d" % r["spec_verify_iterations"])
        if r["finish_reason"]:
            notes.append(str(r["finish_reason"]))
        ttft = ("%.3f" % (1e3 * r["ttft_s"])
                if r["ttft_s"] is not None else "-")
        lines.append("%-4s %-5d %-6d %-9s %-9.3f %-24s %s"
                     % (r["rid"], r["trace_id"], r["spans"], ttft,
                        1e3 * r["tpot_s"], shares, " ".join(notes)))
    t = report["totals"]
    lines.append("%d request(s), %d request spans + %d engine spans; "
                 "%d preemption(s); trees %s"
                 % (t["requests"], t["spans"], t["engine_spans"],
                    t["preemptions"],
                    "connected" if t["connected"] else "BROKEN"))
    return "\n".join(lines)
