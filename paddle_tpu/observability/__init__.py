"""paddle_tpu.observability — unified runtime telemetry.

The reference framework ships a full platform-layer observability stack
(profiler scheduler windows, RecordEvent spans, chrome-trace export); this
package is its metrics half for the TPU build, wired through every
subsystem:

* :mod:`.registry` — process-wide Counter / Gauge / Histogram registry:
  thread-safe, host-side only (never traced — ``float()`` guard), no-op
  singletons when disabled, fixed log-spaced histogram buckets with
  p50/p95/p99 readout.
* :mod:`.catalog` — the declared metric-name catalog (ops_schema-style:
  the default registry rejects undeclared names; a test keeps catalog and
  runtime emission in sync).
* :mod:`.watchdog` — the recompile watchdog over the compile-once jit
  entries (TrainStep, serving decode/prefill, 1F1B): counts compiles,
  warns on budget violations, raises under ``PADDLE_TPU_STRICT_COMPILE=1``.
* :mod:`.exporters` — Prometheus text, JSONL snapshots, chrome-trace
  metric marks injected into the :mod:`paddle_tpu.profiler` stream.
* CLI: ``python -m paddle_tpu.observability dump|serve|tail`` over the
  JSONL snapshot stream (``PADDLE_TPU_METRICS_FILE``).

Import discipline: this package must stay importable before (and without)
jax — the registry is pure stdlib; jax-adjacent pieces (profiler marks)
import lazily.  See OBSERVABILITY.md for the metric catalog and knobs.
"""
from __future__ import annotations

from .catalog import CATALOG
from .registry import (NOOP_COUNTER, NOOP_GAUGE, NOOP_HISTOGRAM, Counter,
                       Gauge, Histogram, Registry, counter, default_registry,
                       flush, gauge, histogram)
from .watchdog import (RecompileError, RecompileWarning, WatchedEntry,
                       compile_counts, watch)

__all__ = [
    "CATALOG", "Counter", "Gauge", "Histogram", "Registry",
    "NOOP_COUNTER", "NOOP_GAUGE", "NOOP_HISTOGRAM",
    "counter", "gauge", "histogram", "default_registry", "flush",
    "RecompileError", "RecompileWarning", "WatchedEntry", "watch",
    "compile_counts",
]
