"""paddle_tpu.observability — unified runtime telemetry.

The reference framework ships a full platform-layer observability stack
(profiler scheduler windows, RecordEvent spans, chrome-trace export); this
package is its metrics half for the TPU build, wired through every
subsystem:

* :mod:`.registry` — process-wide Counter / Gauge / Histogram registry:
  thread-safe, host-side only (never traced — ``float()`` guard), no-op
  singletons when disabled, fixed log-spaced histogram buckets with
  p50/p95/p99 readout.
* :mod:`.catalog` — the declared metric-name catalog (ops_schema-style:
  the default registry rejects undeclared names; a test keeps catalog and
  runtime emission in sync).
* :mod:`.watchdog` — the recompile watchdog over the compile-once jit
  entries (TrainStep, serving decode/prefill, 1F1B): counts compiles,
  warns on budget violations, raises under ``PADDLE_TPU_STRICT_COMPILE=1``.
* :mod:`.exporters` — Prometheus text, JSONL snapshots, chrome-trace
  metric marks injected into the :mod:`paddle_tpu.profiler` stream.
* :mod:`.tracing` — request-scoped span tracing (ISSUE 9): a trace_id
  per serving request, spans with parent links over queue/prefill-chunk/
  decode/verify/preemption phases, chrome-trace + JSONL export, and the
  ``trace-report`` timeline/attribution analyzer.  Disabled by default
  (``PADDLE_TPU_TRACING=1`` arms it — no-op identity tracer otherwise).
* :mod:`.flight` — the black-box flight recorder: a bounded ring of
  recent span/engine events plus metrics + engine-state + HBM-ledger
  snapshots, dumped to a file on DivergenceError / strict
  RecompileError / preemption-guard fires / faultpoint-raised crashes
  (``PADDLE_TPU_FLIGHT=1`` arms it).
* :mod:`.costs` — compiled-program cost reports (ISSUE 11): XLA
  ``cost_analysis()`` + ``memory_analysis()`` extracted into
  :class:`~.costs.ProgramReport` for every canonical-registry program
  and every serving entry, MFU / HBM-bandwidth-utilization derivation,
  and the schema'd bench ``cost`` block.
* :mod:`.hbm` — the live HBM ledger: catalog'd gauges for per-device
  live bytes / engine KV-pool bytes / checkpoint-restore transients,
  sampled at step boundaries when armed (``PADDLE_TPU_HBM=1``), with
  chrome-trace counter lanes and flight-dump snapshots.
* :mod:`.liveness` — the liveness watchdog (ISSUE 14): named progress
  beacons at every hot boundary (train step, fit batch, scheduler
  step, frontend threads, checkpoint writer, store ops, autotune),
  watched by a monitor thread with per-beacon deadlines; a stall dumps
  all-thread stacks into a ``"stall"`` flight dump, increments
  ``liveness.stalls{beacon=}``, and can hard-exit with a configurable
  rc so the elastic launcher respawns the wedged worker
  (``PADDLE_TPU_LIVENESS=1`` arms it — no-op beacon singleton
  otherwise).
* :mod:`.aggregate` — cross-host telemetry (ISSUE 14): per-host
  snapshot publication through the retry-wrapped distributed store and
  the host-0 cluster merge with step-time straggler detection
  (``liveness.straggler{host=}``).
* CLI: ``python -m paddle_tpu.observability
  dump|serve|tail|trace-report|programs|cluster`` over the JSONL
  snapshot stream (``PADDLE_TPU_METRICS_FILE``), span trace files, the
  canonical program registry, and the distributed-store telemetry
  keys.

Import discipline: this package must stay importable before (and without)
jax — the registry is pure stdlib; jax-adjacent pieces (profiler marks)
import lazily.  See OBSERVABILITY.md for the metric catalog and knobs.
"""
from __future__ import annotations

from . import aggregate, costs, flight, hbm, liveness
from .catalog import CATALOG
from .registry import (NOOP_COUNTER, NOOP_GAUGE, NOOP_HISTOGRAM, Counter,
                       Gauge, Histogram, Registry, counter, default_registry,
                       flush, gauge, histogram)
from .tracing import NOOP_SPAN, NOOP_TRACER, Tracer, default_tracer
from .watchdog import (RecompileError, RecompileWarning, WatchedEntry,
                       compile_counts, watch)

__all__ = [
    "CATALOG", "Counter", "Gauge", "Histogram", "Registry",
    "NOOP_COUNTER", "NOOP_GAUGE", "NOOP_HISTOGRAM",
    "counter", "gauge", "histogram", "default_registry", "flush",
    "RecompileError", "RecompileWarning", "WatchedEntry", "watch",
    "compile_counts",
    "Tracer", "NOOP_TRACER", "NOOP_SPAN", "default_tracer", "flight",
    "costs", "hbm", "liveness", "aggregate",
]
