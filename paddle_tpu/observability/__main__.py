"""CLI over the JSONL metric-snapshot stream and span-trace files.

    python -m paddle_tpu.observability dump  [--file P] [--format prom|json]
    python -m paddle_tpu.observability tail  [--file P] [--follow] [--interval S]
    python -m paddle_tpu.observability serve [--file P] [--port N]
    python -m paddle_tpu.observability trace-report --file T \\
        [--format table|json] [--chrome OUT] [--allow-empty] [--sli]
    python -m paddle_tpu.observability programs [patterns] \\
        [--format table|json]
    python -m paddle_tpu.observability cluster [--master host:port] \\
        [--world N] [--pct P] [--format table|json]

``trace-report`` (ISSUE 9) reconstructs per-request timelines from a
span trace (the JSONL a :class:`~.tracing.Tracer` exports — see
``bench_decode.py --trace-file``) and prints TTFT/TPOT attribution
(queue vs prefill vs decode vs preemption-rework share) per request;
``--chrome OUT`` additionally writes the chrome://tracing JSON with one
lane per request; ``--sli`` adds the per-finish-reason p50/p99
TTFT/TPOT rollup (cross-checked in tests against the ISSUE-6 histograms
on the same run).  Exit 2 when the file holds no request traces (unless
``--allow-empty``), exit 1 when any request's span tree is
disconnected — CI uses both as hard gates.

``programs`` (ISSUE 11) prices the trace-audit canonical registry with
XLA's own cost/memory analysis: one FLOPs / bytes-accessed / peak-HBM
row per program (:mod:`.costs`).  Same operational discipline as the
``--trace`` analysis CLI: an empty registry exits 2 (never silent
green), broken builders exit 1, and the process must be launched with
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` off-chip so the
pipeline program gets its mesh (CI does).

``cluster`` (ISSUE 14) renders the merged cross-host view: it connects
a client to the distributed store every host publishes its telemetry
snapshot through (:mod:`.aggregate`), fetches all ``world`` hosts'
newest snapshots, and prints the per-host step-time table with
straggler flags (> ``--pct`` percent over the cluster median) and
stalled-beacon columns.  Exit 2 when NO host has published (never
silent green), exit 1 when some hosts are missing — a wedged worker
that stopped publishing is the loudest row in the table.

``--file`` defaults to ``$PADDLE_TPU_METRICS_FILE``.  ``dump`` renders the
newest snapshot (Prometheus text by default); with no file configured it
renders the current in-process default registry (useful after ``python -c
"import workload; ..."``-style drivers).  ``tail`` prints one compact line
per snapshot (and keeps following with ``--follow``).  ``serve`` exposes
the newest snapshot at ``/metrics`` in Prometheus text format — point a
scraper at a training/serving host without linking any client library.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

from . import exporters, registry


def _latest_snapshot(path):
    """(ts, metrics) from the last well-formed line of a JSONL file."""
    last = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                last = line
    if last is None:
        return None, None
    doc = json.loads(last)
    return doc.get("ts"), doc.get("metrics", {})


def _render(metrics, fmt):
    if fmt == "json":
        return json.dumps(metrics, indent=1, sort_keys=True)
    return exporters.to_prometheus(snapshot=metrics)


def _summarize(doc) -> str:
    """One compact human line per snapshot for ``tail``."""
    metrics = doc.get("metrics", {})
    parts = []
    for name, entry in sorted(metrics.items()):
        for series in entry["series"]:
            labels = series.get("labels", {})
            key = name + ("{%s}" % ",".join("%s=%s" % kv for kv in
                                            sorted(labels.items()))
                          if labels else "")
            if entry["type"] == "histogram":
                parts.append("%s: n=%d p50=%.4g p99=%.4g"
                             % (key, series["count"], series["p50"],
                                series["p99"]))
            else:
                parts.append("%s=%.6g" % (key, series["value"]))
    ts = doc.get("ts")
    stamp = time.strftime("%H:%M:%S", time.localtime(ts)) if ts else "-"
    return "[%s] %s" % (stamp, "  ".join(parts) or "(empty)")


def cmd_dump(args) -> int:
    if args.file:
        try:
            _ts, metrics = _latest_snapshot(args.file)
        except FileNotFoundError:
            print("no snapshots in %s (file does not exist)" % args.file,
                  file=sys.stderr)
            return 1
        if metrics is None:
            print("no snapshots in %s" % args.file, file=sys.stderr)
            return 1
        print(_render(metrics, args.format), end="")
    else:
        print(_render(registry.default_registry().snapshot(), args.format),
              end="")
    return 0


def cmd_tail(args) -> int:
    if not args.file:
        print("tail needs --file or PADDLE_TPU_METRICS_FILE",
              file=sys.stderr)
        return 2
    pos = 0
    try:
        while True:
            if os.path.exists(args.file):
                with open(args.file) as f:
                    f.seek(pos)
                    while True:
                        line = f.readline()
                        if not line.endswith("\n"):
                            break  # torn tail line: re-read next round
                        pos = f.tell()
                        if not line.strip():
                            continue
                        try:
                            print(_summarize(json.loads(line)))
                        except json.JSONDecodeError:
                            pass  # malformed line: skip, keep following
            if not args.follow:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def make_server(path, port=0, in_process=False):
    """The ``serve`` HTTP server (returned unstarted so tests can drive it
    on an ephemeral port).  ``GET /metrics`` -> Prometheus text of the
    newest snapshot (or the live in-process registry)."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.split("?")[0] not in ("/", "/metrics"):
                self.send_error(404)
                return
            try:
                if in_process or not path:
                    body = exporters.to_prometheus(
                        registry.default_registry())
                else:
                    _ts, metrics = _latest_snapshot(path)
                    body = _render(metrics or {}, "prom")
            except FileNotFoundError:
                body = ""
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, fmt, *a):
            pass  # no per-request stderr spam

    return HTTPServer(("127.0.0.1", port), Handler)


def cmd_trace_report(args) -> int:
    from . import tracing
    if not args.file:
        print("trace-report needs --file (a Tracer JSONL export) or "
              "PADDLE_TPU_TRACE_FILE", file=sys.stderr)
        return 2
    try:
        spans, events, _metas = tracing.load_trace(args.file)
    except FileNotFoundError:
        print("no trace at %s" % args.file, file=sys.stderr)
        return 2
    report = tracing.build_report(spans, events)
    if args.chrome:
        tracing.write_chrome(args.chrome, spans, events,
                             include_profiler=False)
        print("chrome trace written to %s" % args.chrome,
              file=sys.stderr)
    if not report["requests"] and not args.allow_empty:
        print("no request traces in %s (0 spans with a 'request' root)"
              % args.file, file=sys.stderr)
        return 2
    if args.sli:
        report["sli"] = tracing.build_sli(report)
    if args.format == "json":
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(tracing.format_report(report))
        if args.sli:
            print()
            print(tracing.format_sli(report["sli"]))
    if not report["totals"]["connected"]:
        print("trace-report: DISCONNECTED span tree(s) — a span's "
              "parent link does not reach its request root",
              file=sys.stderr)
        return 1
    return 0


def cmd_programs(args) -> int:
    """Price the canonical registry (``--trace`` CLI discipline: empty =
    exit 2, broken builders = exit 1, skips are loud warnings)."""
    from . import costs
    reports, skipped, errors = costs.registry_reports(
        args.patterns or None)
    for s in skipped:
        print("WARNING: builder skipped — %s\n  (off-chip runs need "
              "shell-level XLA_FLAGS=--xla_force_host_platform_device_"
              "count=8 set BEFORE jax initializes)" % s, file=sys.stderr)
    for e in errors:
        print("ERROR: %s" % e, file=sys.stderr)
    if not reports:
        print("programs: EMPTY registry%s — refusing to look green"
              % (" for patterns %r" % (args.patterns,)
                 if args.patterns else ""), file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps([r.as_dict() for r in reports], indent=1,
                         sort_keys=True))
    else:
        print(costs.format_table(reports))
    return 1 if errors else 0


def cmd_cluster(args) -> int:
    """The merged cross-host telemetry table (``--trace`` CLI
    discipline: an empty cluster exits 2, partial publication exits 1,
    both loud)."""
    from . import aggregate
    if not args.master:
        print("cluster needs --master host:port (or PADDLE_MASTER)",
              file=sys.stderr)
        return 2
    host, _, port = args.master.rpartition(":")
    if not host or not port.isdigit():
        print("cluster: malformed --master %r (want host:port)"
              % args.master, file=sys.stderr)
        return 2
    from ..distributed.store import TCPStore
    try:
        store = TCPStore(host, int(port), is_master=False,
                         world_size=args.world, timeout=args.timeout)
        docs, missing = aggregate.fetch_cluster(store, args.world)
    except (ConnectionError, OSError, RuntimeError) as e:
        # a dead/unreachable master is the exit-2 case (nothing could
        # be fetched), NOT exit 1 ("some hosts missing") — an operator
        # script keying on the rc must be able to tell them apart
        print("cluster: cannot reach the store at %s: %s"
              % (args.master, e), file=sys.stderr)
        return 2
    if not docs:
        print("cluster: NO host has published telemetry (of %d) — "
              "publishers not started, wrong --master, or the whole "
              "fleet is wedged" % args.world, file=sys.stderr)
        return 2
    doc = aggregate.merge_docs(docs, args.world, pct=args.pct,
                               set_gauges=False)
    if args.format == "json":
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        print(aggregate.format_cluster(doc))
    if missing:
        print("cluster: %d host(s) missing: %s" % (len(missing), missing),
              file=sys.stderr)
        return 1
    return 0


def cmd_serve(args) -> int:
    srv = make_server(args.file, args.port)
    print("serving /metrics on http://127.0.0.1:%d (source: %s)"
          % (srv.server_address[1], args.file or "in-process registry"))
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m paddle_tpu.observability")
    sub = p.add_subparsers(dest="cmd", required=True)
    default_file = os.environ.get("PADDLE_TPU_METRICS_FILE")

    d = sub.add_parser("dump", help="print the newest snapshot")
    d.add_argument("--file", default=default_file)
    d.add_argument("--format", choices=("prom", "json"), default="prom")
    d.set_defaults(fn=cmd_dump)

    t = sub.add_parser("tail", help="print one line per snapshot")
    t.add_argument("--file", default=default_file)
    t.add_argument("--follow", action="store_true")
    t.add_argument("--interval", type=float, default=1.0)
    t.set_defaults(fn=cmd_tail)

    s = sub.add_parser("serve", help="HTTP /metrics endpoint")
    s.add_argument("--file", default=default_file)
    s.add_argument("--port", type=int, default=9464)
    s.set_defaults(fn=cmd_serve)

    r = sub.add_parser("trace-report",
                       help="per-request timeline + TTFT/TPOT "
                            "attribution from a span trace file")
    r.add_argument("--file",
                   default=os.environ.get("PADDLE_TPU_TRACE_FILE"))
    r.add_argument("--format", choices=("table", "json"),
                   default="table")
    r.add_argument("--chrome", default=None, metavar="OUT",
                   help="also write chrome://tracing JSON (one lane per "
                        "request) to OUT")
    r.add_argument("--allow-empty", action="store_true",
                   help="exit 0 even when the file holds no request "
                        "traces")
    r.add_argument("--sli", action="store_true",
                   help="add the per-finish-reason p50/p99 TTFT/TPOT "
                        "rollup (table mode prints it after the "
                        "per-request table; json mode adds an 'sli' key)")
    r.set_defaults(fn=cmd_trace_report)

    g = sub.add_parser("programs",
                       help="FLOPs/bytes/peak-HBM report over the "
                            "trace-audit canonical program registry "
                            "(XLA cost/memory analysis)")
    g.add_argument("patterns", nargs="*",
                   help="optional fnmatch filters on program names "
                        "(e.g. 'serving/*')")
    g.add_argument("--format", choices=("table", "json"),
                   default="table")
    g.set_defaults(fn=cmd_programs)

    c = sub.add_parser("cluster",
                       help="merged cross-host telemetry view from the "
                            "distributed store (per-host step times, "
                            "straggler flags, stalled beacons, missing "
                            "hosts)")
    c.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="the distributed store endpoint host:port "
                        "(default: $PADDLE_MASTER)")
    c.add_argument("--world", type=int,
                   default=int(os.environ.get("PADDLE_TRAINERS_NUM",
                                              "1")),
                   help="hosts expected to publish (default: "
                        "$PADDLE_TRAINERS_NUM)")
    c.add_argument("--timeout", type=float, default=10.0,
                   help="seconds to keep dialing an unreachable store "
                        "before exiting 2")
    c.add_argument("--pct", type=float, default=None,
                   help="straggler threshold: flag hosts whose step p50 "
                        "exceeds the median by more than this percent "
                        "(default 25, or $PADDLE_TPU_STRAGGLER_PCT)")
    c.add_argument("--format", choices=("table", "json"),
                   default="table")
    c.set_defaults(fn=cmd_cluster)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
