"""The recompile watchdog — turn silent retraces into a loud runtime signal.

The serving engine's headline bug class (PR 5: a per-token retrace of the
decode step that cost ~100x throughput and was invisible for five PRs) is
structural: jax.jit happily compiles a fresh program for every new
argument-shape/dtype signature, and nothing in the runtime says so.  The
watchdog instruments the compile-once entry points — ``TrainStep``,
serving decode/prefill, the 1F1B pipeline step — by checking the jit's
program-cache size after every call:

* every growth increments ``compile.count{entry=<name>}`` in the default
  metrics registry (so bench JSON lines and Prometheus scrapes carry
  compile counts from now on), and
* growth past the entry's ``expected`` budget emits ONE structured
  :class:`RecompileWarning` per excess compile — or raises
  :class:`RecompileError` immediately under ``PADDLE_TPU_STRICT_COMPILE=1``
  (the CI bench-smoke mode).

``watch()`` wraps the jitted callable transparently: attribute access
(``_cache_size``, ``lower``, ...) is delegated, so existing audit hooks
and compile-count properties keep working on a watched entry.
"""
from __future__ import annotations

import json
import os
import threading
import warnings
import weakref
from typing import Callable, Dict, Optional

from . import registry as _registry

__all__ = ["RecompileWarning", "RecompileError", "WatchedEntry", "watch",
           "compile_counts", "resync_counter", "strict_mode"]


class RecompileWarning(UserWarning):
    """A supposedly compile-once jit entry compiled again at runtime."""


class RecompileError(RuntimeError):
    """Strict-mode (PADDLE_TPU_STRICT_COMPILE=1) recompile failure.

    Fatal by design — a CI/bench kill switch, not a recoverable signal:
    the offending call has already EXECUTED when the cache growth is
    detected, so for entries with donated operands (TrainStep, serving
    decode) the caller's input buffers are consumed and the step's output
    is discarded with the raise.  Catching this to log-and-continue will
    hit deleted-buffer errors on the next call; let it terminate the run.
    """


def strict_mode() -> bool:
    return os.environ.get("PADDLE_TPU_STRICT_COMPILE", "0") not in (
        "0", "", "false", "off")


#: process-wide table of watched entries: name -> [weakref, ...] (several
#: engines may watch the same logical entry name; counts sum).  Weak on
#: purpose: a WatchedEntry holds the jit, which holds its compiled
#: programs AND the model closure — a strong global table would pin every
#: TrainStep/engine ever built for the life of the process.
_ENTRIES: Dict[str, list] = {}
_ENTRIES_LOCK = threading.Lock()


class WatchedEntry:
    """A jitted callable plus its compile budget.  Call it like the jit;
    every program-cache growth is metered and budget-checked."""

    def __init__(self, name: str, fn: Callable,
                 expected: Optional[int] = None):
        self._name = name
        self._fn = fn
        self._expected = expected
        self._seen = self._raw_cache_size()
        self._counter = _registry.counter("compile.count", ("entry",))
        self._lock = threading.Lock()
        with _ENTRIES_LOCK:
            refs = _ENTRIES.setdefault(name, [])
            refs[:] = [r for r in refs if r() is not None]
            refs.append(weakref.ref(self))

    # -- introspection -----------------------------------------------------

    @property
    def entry_name(self) -> str:
        return self._name

    @property
    def compile_count(self) -> int:
        """Programs this entry's jit cache holds right now."""
        return self._raw_cache_size()

    def _raw_cache_size(self) -> int:
        try:
            return int(self._fn._cache_size())
        except Exception:
            return 0

    def __getattr__(self, name):
        # transparent delegation: audit hooks (.lower), the engine's
        # _cache_size-based properties, functools metadata all pass through
        fn = self.__dict__.get("_fn")
        if fn is None:
            raise AttributeError(name)
        return getattr(fn, name)

    # -- the metered call --------------------------------------------------

    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        n = self._raw_cache_size()
        if n != self._seen:
            self._on_growth(n)
        return out

    def _on_growth(self, n: int):
        with self._lock:
            grew = n - self._seen
            if grew <= 0:       # cache cleared/shrunk: resync, no event
                self._seen = n
                return
            self._seen = n
        self._counter.labels(entry=self._name).inc(grew)
        from . import flight as _flight
        _flight.record("recompile", entry=self._name, compile_count=n,
                       expected=self._expected)
        if self._expected is not None and n > self._expected:
            payload = json.dumps({
                "event": "recompile", "entry": self._name,
                "compile_count": n, "expected": self._expected}, sort_keys=True)
            if strict_mode():
                # black-box dump BEFORE the raise: the strict error is
                # fatal by design, so this is the post-mortem's one shot
                # at the ring + engine state (no-op unless armed)
                _flight.crash_dump({
                    "kind": "recompile", "entry": self._name,
                    "compile_count": n, "expected": self._expected})
                raise RecompileError(
                    "compile-once violation: %s — the jit entry %r now "
                    "holds %d programs (budget %d); an argument "
                    "shape/dtype/structure is varying across calls"
                    % (payload, self._name, n, self._expected))
            warnings.warn(
                "RECOMPILE %s — entry %r compiled %d time(s) against a "
                "budget of %d; a supposedly-static argument is varying "
                "(set PADDLE_TPU_STRICT_COMPILE=1 to make this fatal)"
                % (payload, self._name, n, self._expected),
                RecompileWarning, stacklevel=3)


def watch(name: str, fn: Callable,
          expected: Optional[int] = None) -> WatchedEntry:
    """Wrap a jitted callable as a watched entry.  ``expected`` is the
    compile budget (1 for compile-once entries, ``len(buckets)`` for the
    bucketed prefill, None to meter without a budget)."""
    return WatchedEntry(name, fn, expected)


def compile_counts() -> Dict[str, int]:
    """{entry name: total programs held} across every live watched entry
    in the process — what bench.py / bench_decode.py attach to their JSON
    lines."""
    with _ENTRIES_LOCK:
        items = [(name, [e for e in (r() for r in refs) if e is not None])
                 for name, refs in sorted(_ENTRIES.items())]
    return {name: sum(e.compile_count for e in entries)
            for name, entries in items if entries}


def resync_counter():
    """Re-align ``compile.count{entry=}`` with the live jit cache sizes.

    The watchdog's ground truth is the cache size; the registry counter is
    its exported shadow.  After ``Registry.reset()`` (e.g. a bench dropping
    warmup samples) the shadow reads 0 while the caches still hold their
    programs — call this to bring Prometheus/JSONL exports back into
    agreement with :func:`compile_counts`."""
    c = _registry.counter("compile.count", ("entry",))
    for name, n in compile_counts().items():
        leaf = c.labels(entry=name)
        delta = n - leaf.value
        if delta > 0:
            leaf.inc(delta)
