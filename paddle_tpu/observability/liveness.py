"""The liveness watchdog: stall detection for a process that is alive
but no longer making progress.

The rest of the observability stack fires on *crashes* — faultpoint
raises, strict :class:`~.watchdog.RecompileError`, divergence, a
preemption notice.  A production fleet's worst failures are *hangs*: a
wedged collective, a stuck NFS checkpoint write, a deadlocked frontend
thread, a straggler host dragging every synchronous step.  Those produce
zero signal until an external timeout kills the job — and the postmortem
then holds nothing, because the process never "failed".

This module plants named progress **beacons** at every hot boundary
(TrainStep, the hapi fit batch loop, the serving scheduler step, the
frontend loop/scheduler threads, the checkpoint writer, store client
ops, autotune timed runs) and watches them from a monitor thread:

* A :class:`Beacon` is a monotonic progress counter + a
  ``perf_counter_ns`` stamp + an *inflight* depth.  Instrumented code
  either wraps one bounded operation in ``with beacon:`` (enter stamps
  and raises inflight; exit stamps, counts, lowers it) or, for
  long-running loops, calls :meth:`Beacon.begin` once and
  :meth:`Beacon.pulse` per iteration.  A beacon is only *watched* while
  ``inflight > 0`` — an idle subsystem (no save queued, server drained)
  never false-positives.
* The :class:`LivenessMonitor` thread checks every beacon against its
  deadline (global ``PADDLE_TPU_LIVENESS_DEADLINE``, per-beacon
  ``PADDLE_TPU_LIVENESS_DEADLINE_<NAME>`` with dots spelled as
  underscores, or the default declared with the beacon).  On a stall it
  dumps **all-thread stacks** (via :func:`faulthandler.dump_traceback`)
  to stderr AND into a flight dump with a ``"stall"`` trigger naming
  the stalled beacon (plus the HBM ledger state every flight dump
  embeds), increments the catalog'd ``liveness.stalls{beacon=}``
  counter, and — when ``PADDLE_TPU_LIVENESS_EXIT_RC`` is set —
  hard-exits with that rc so the elastic launcher respawns the worker
  under its normal crash-restart budget (a hung worker becomes a
  restartable crash instead of a silent wedge).
* A fired stall re-arms only after the beacon makes progress (any new
  stamp), so a 10-minute hang produces one dump, not one per poll.

Disabled by default (registry/tracer/ledger discipline): with no
monitor installed :func:`beacon` hands out the module-level
:data:`NOOP_BEACON` singleton **by identity** — instrumented hot loops
that fetched their handle once pay one empty method call and allocate
nothing (tests assert the identity on the scheduler hot loop).  Arm
with ``PADDLE_TPU_LIVENESS=1`` or :func:`enable`.

Beacons are *declared* (:func:`declare_beacon`) at import time of the
instrumented module, faultpoint-site style: :data:`BEACONS` mirrors the
instrumentation, ``liveness.stalls``'s label space stays bounded, and a
typo'd beacon name fails at fetch time instead of silently never being
watched.

Cross-host aggregation of beacon ages and step-time summaries lives in
:mod:`.aggregate`; see OBSERVABILITY.md for the dump format and knobs.
"""
from __future__ import annotations

import faulthandler
import os
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

from . import registry as _registry

__all__ = [
    "Beacon", "NoopBeacon", "NOOP_BEACON", "LivenessMonitor",
    "BEACONS", "declare_beacon", "beacon", "enable", "disable",
    "active", "state", "deadline_for", "all_thread_stacks",
    "DEADLINE_DEFAULT",
]

#: global default deadline (seconds) when neither the env nor the
#: declaration specifies one.  Generous: the first pass through a jitted
#: boundary pays an XLA compile.
DEADLINE_DEFAULT = 300.0

#: name -> {"doc", "deadline"}: every declared beacon (the instrumented
#: module declares at import time, so this registry mirrors the
#: instrumentation — OBSERVABILITY.md documents it, the liveness suite
#: asserts against it).
BEACONS: Dict[str, dict] = {}

_ACTIVE: Optional["LivenessMonitor"] = None
_LOCK = threading.Lock()

#: beacons of the most recently stopped monitor — a disable()/enable()
#: cycle must not orphan handles components cached at construction (the
#: same carry-over enable() does for a live replacement)
_CARRIED_BEACONS: Dict[str, "Beacon"] = {}


def declare_beacon(name: str, doc: str = "",
                   deadline: Optional[float] = None) -> str:
    """Register a beacon name (idempotent), with an optional default
    deadline.  Called at import time by the instrumented module."""
    prev = BEACONS.get(name, {})
    BEACONS[name] = {
        "doc": doc or prev.get("doc", ""),
        "deadline": deadline if deadline is not None
        else prev.get("deadline"),
    }
    return name


def all_thread_stacks() -> str:
    """Every thread's current stack, one faulthandler-formatted block
    per thread.  faulthandler needs a real fd, so this round-trips
    through an anonymous temp file; never raises (a postmortem helper
    must not mask the fault being reported)."""
    try:
        with tempfile.TemporaryFile(mode="w+") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
            f.seek(0)
            return f.read()
    except Exception as e:  # pragma: no cover - faulthandler/IO failure
        return "<all_thread_stacks failed: %r>" % (e,)


# ---------------------------------------------------------------------------
# beacons
# ---------------------------------------------------------------------------

class Beacon:
    """One named progress marker.  ``with beacon:`` brackets a bounded
    operation (watched while inside); :meth:`pulse` marks progress from
    inside a long-running guarded loop; :meth:`begin`/:meth:`done` are
    the explicit spelling for loops without a ``with``-shaped scope
    (the frontend loop-thread heartbeat).

    A beacon is shared by every caller of its name, so the stall clock
    is tracked **per inflight entry** (one stamp per outstanding
    begin, keyed per thread): a wedged op cannot be masked by sibling
    ops on the same beacon completing or pulsing — the watchdog watches
    the OLDEST outstanding entry, and only its own thread's
    :meth:`pulse` refreshes it."""

    __slots__ = ("name", "count", "last_ns", "_lock", "_entries",
                 "_next_id", "_tls")

    def __init__(self, name: str):
        self.name = name
        self.count = 0                     # completed ops / pulses
        self.last_ns = time.perf_counter_ns()
        self._lock = threading.Lock()
        self._entries: Dict[int, int] = {}   # entry id -> stamp_ns
        self._next_id = 0
        self._tls = threading.local()        # per-thread entry-id stack

    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- progress marks ----------------------------------------------------

    def pulse(self):
        """Mark progress (and re-stamp this thread's innermost
        outstanding entry, if any) without changing inflight."""
        now = time.perf_counter_ns()
        st = self._stack()
        with self._lock:
            self.count += 1
            self.last_ns = now
            if st and st[-1] in self._entries:
                self._entries[st[-1]] = now

    def begin(self):
        now = time.perf_counter_ns()
        with self._lock:
            eid = self._next_id
            self._next_id += 1
            self._entries[eid] = now
            self.last_ns = now
        self._stack().append(eid)
        return self

    def done(self):
        st = self._stack()
        eid = st.pop() if st else None
        with self._lock:
            if eid is not None:
                self._entries.pop(eid, None)
            self.count += 1
            self.last_ns = time.perf_counter_ns()

    def __enter__(self):
        return self.begin()

    def __exit__(self, *exc):
        # an op that RAISED still completed (the failure surfaces through
        # its own channel) — only a hang is a stall
        self.done()
        return False

    # -- readout -----------------------------------------------------------

    @property
    def inflight(self) -> int:
        with self._lock:
            return len(self._entries)

    def oldest_ns(self) -> Optional[int]:
        """Stamp of the oldest outstanding entry (None when idle) — the
        stall clock: refreshed only by that entry's own progress."""
        with self._lock:
            return min(self._entries.values()) if self._entries else None

    def age_s(self, now_ns: Optional[int] = None) -> float:
        """Seconds since the oldest outstanding entry's stamp (watched),
        or since the last completion (idle)."""
        now_ns = time.perf_counter_ns() if now_ns is None else now_ns
        oldest = self.oldest_ns()
        ref = oldest if oldest is not None else self.last_ns
        return max(now_ns - ref, 0) * 1e-9


class NoopBeacon:
    """The disabled-path beacon: every method is a constant no-op (the
    registry's NOOP_* discipline — assertable by identity)."""

    __slots__ = ()
    name = "<noop>"
    count = 0
    inflight = 0

    def pulse(self):
        pass

    def begin(self):
        return self

    def done(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def oldest_ns(self):
        return None

    def age_s(self, now_ns=None):
        return 0.0


#: the singleton a disabled liveness stack hands out — instrumented code
#: asserts the fast path by identity.
NOOP_BEACON = NoopBeacon()


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------

def _env_float(name: str) -> Optional[float]:
    """Degrade-loudly env parse: a typo'd observability knob must never
    crash `import paddle_tpu`, kill a monitor poll, or blank /healthz —
    it warns on stderr once per read and falls through to the next
    resolution tier (the PADDLE_TPU_FLIGHT_SIGNAL discipline)."""
    v = os.environ.get(name)
    if v in (None, ""):
        return None
    try:
        return float(v)
    except ValueError:
        sys.stderr.write("[liveness] %s ignored: %r is not a float\n"
                         % (name, v))
        return None


def _env_name(beacon_name: str) -> str:
    return ("PADDLE_TPU_LIVENESS_DEADLINE_"
            + beacon_name.upper().replace(".", "_"))


def _resolve_deadline(name: str, fallback: float) -> float:
    """THE deadline resolution chain (one copy): per-beacon env >
    declared default > ``fallback`` (the caller's global default)."""
    env = _env_float(_env_name(name))
    if env is not None:
        return env
    declared = BEACONS.get(name, {}).get("deadline")
    if declared is not None:
        return float(declared)
    return fallback


class LivenessMonitor:
    """Watches every fetched beacon from a daemon thread.

    ``deadline``/``poll``/``exit_rc`` override the env knobs
    (``PADDLE_TPU_LIVENESS_DEADLINE`` / ``_POLL`` / ``_EXIT_RC``);
    tests pass ``start=False`` to :func:`enable` and drive
    :meth:`check_now` deterministically."""

    def __init__(self, deadline: Optional[float] = None,
                 poll: Optional[float] = None,
                 exit_rc: Optional[int] = None):
        d = deadline if deadline is not None else _env_float(
            "PADDLE_TPU_LIVENESS_DEADLINE")
        self.default_deadline = float(d) if d is not None \
            else DEADLINE_DEFAULT
        p = poll if poll is not None else _env_float(
            "PADDLE_TPU_LIVENESS_POLL")
        self.poll = float(p) if p is not None \
            else max(min(self.default_deadline / 4.0, 5.0), 0.01)
        if exit_rc is None:
            rc = os.environ.get("PADDLE_TPU_LIVENESS_EXIT_RC")
            if rc not in (None, ""):
                try:
                    exit_rc = int(rc)
                except ValueError:
                    sys.stderr.write(
                        "[liveness] PADDLE_TPU_LIVENESS_EXIT_RC ignored:"
                        " %r is not an int\n" % (rc,))
        self.exit_rc = exit_rc
        self._beacons: Dict[str, Beacon] = {}
        self._lock = threading.Lock()
        # beacon -> last_ns observed when its stall fired: re-arm only
        # after the beacon re-stamps (one dump per hang, not per poll)
        self._fired_stamp: Dict[str, int] = {}
        self.stall_log: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_stalls = _registry.counter("liveness.stalls", ("beacon",))

    # -- beacon fetch ------------------------------------------------------

    def beacon(self, name: str) -> Beacon:
        if name not in BEACONS:
            raise ValueError(
                "unknown liveness beacon %r — declared beacons: %s "
                "(declare_beacon() test-local names before fetching "
                "them)" % (name, sorted(BEACONS)))
        with self._lock:
            b = self._beacons.get(name)
            if b is None:
                b = Beacon(name)
                self._beacons[name] = b
        return b

    def deadline_for(self, name: str) -> float:
        # per-beacon env re-read live; the GLOBAL default was seeded at
        # construction (enable() replaces the monitor to change it)
        return _resolve_deadline(name, self.default_deadline)

    # -- stall detection ---------------------------------------------------

    def state(self) -> Dict[str, dict]:
        """Per-beacon liveness view (the /healthz + aggregation
        payload): count, inflight, age, deadline, stalled — computed on
        read, so a probe sees the stall as soon as the age crosses the
        deadline even between monitor polls."""
        now_ns = time.perf_counter_ns()
        with self._lock:
            beacons = dict(self._beacons)
        out = {}
        for name, b in sorted(beacons.items()):
            deadline = self.deadline_for(name)
            age = b.age_s(now_ns)
            out[name] = {
                "count": b.count,
                "inflight": b.inflight,
                "age_s": round(age, 6),
                "deadline_s": deadline,
                "stalled": bool(b.inflight > 0 and age > deadline),
            }
        return out

    def check_now(self, now_ns: Optional[int] = None) -> List[dict]:
        """One monitor pass; returns the stalls fired (tests drive this
        directly with ``enable(start=False)``)."""
        now_ns = time.perf_counter_ns() if now_ns is None else now_ns
        with self._lock:
            beacons = list(self._beacons.values())
        fired = []
        for b in beacons:
            # the stall clock is the OLDEST outstanding entry's own
            # stamp: sibling ops completing/pulsing on the shared
            # beacon cannot mask a wedged one
            stamp = b.oldest_ns()
            if stamp is None:              # idle: unwatched
                continue
            deadline = self.deadline_for(b.name)
            age = max(now_ns - stamp, 0) * 1e-9
            if age <= deadline:
                continue
            with self._lock:
                # check_now runs on the monitor thread AND directly on
                # callers' threads (tests, manual probes): the fired-
                # stamp dedup must be atomic or one hang reports twice
                if self._fired_stamp.get(b.name) == stamp:
                    continue               # already reported this hang
                self._fired_stamp[b.name] = stamp
            fired.append(self._fire_stall(b, age, deadline))
        return fired

    def _fire_stall(self, b: Beacon, age: float, deadline: float) -> dict:
        """The postmortem: all-thread stacks + flight dump + counter
        (+ optional hard exit).  Never raises — a broken postmortem
        must not take down a process that might still recover."""
        from . import flight as _flight
        stacks = all_thread_stacks()
        info = {
            "kind": "stall", "beacon": b.name,
            "age_s": round(age, 3), "deadline_s": deadline,
            "count": b.count, "inflight": b.inflight,
        }
        try:
            sys.stderr.write(
                "[liveness] STALL: beacon %r made no progress for %.1fs "
                "(deadline %.1fs, %d completed, %d inflight) — all-thread "
                "stacks follow\n%s" % (b.name, age, deadline, b.count,
                                       b.inflight, stacks))
            sys.stderr.flush()
        except Exception:
            pass
        try:
            self._m_stalls.labels(beacon=b.name).inc()
        except Exception:
            pass
        try:
            fields = {k: v for k, v in info.items() if k != "kind"}
            _flight.record("stall", **fields)
            path = _flight.crash_dump(dict(info, stacks=stacks))
            info["dump"] = path
        except Exception:
            info["dump"] = None
        self.stall_log.append(info)
        if self.exit_rc is not None:
            sys.stderr.write(
                "[liveness] hard-exiting rc=%d so the launcher can "
                "respawn this worker (PADDLE_TPU_LIVENESS_EXIT_RC)\n"
                % self.exit_rc)
            sys.stderr.flush()
            os._exit(self.exit_rc)
        return info

    # -- thread lifecycle --------------------------------------------------

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="liveness-monitor", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 2.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def _run(self):
        while not self._stop.wait(self.poll):
            try:
                self.check_now()
            except Exception as e:  # pragma: no cover - defensive
                sys.stderr.write("[liveness] monitor pass failed: %r\n"
                                 % (e,))


# ---------------------------------------------------------------------------
# module-level API (what the instrumented subsystems call)
# ---------------------------------------------------------------------------

def enable(deadline: Optional[float] = None, poll: Optional[float] = None,
           exit_rc: Optional[int] = None,
           start: bool = True) -> LivenessMonitor:
    """Install (or replace) the process-wide monitor.  Beacons fetched
    while disabled are the shared no-op singleton forever (the
    registry's zero-cost contract) — arm liveness BEFORE constructing
    the components to watch (the env knob arms at import).  Replacing
    a LIVE monitor (e.g. to change the exit rc) — or re-enabling after
    a disable() — carries the previous beacon map over: components
    cached their handles at construction, and a fresh empty map would
    silently orphan every one of them."""
    global _ACTIVE
    with _LOCK:
        mon = LivenessMonitor(deadline=deadline, poll=poll,
                              exit_rc=exit_rc)
        carried = dict(_CARRIED_BEACONS)
        if _ACTIVE is not None:
            _ACTIVE.stop()
            with _ACTIVE._lock:
                carried.update(_ACTIVE._beacons)
        _CARRIED_BEACONS.clear()
        with mon._lock:
            mon._beacons.update(carried)
        _ACTIVE = mon
        if start:
            _ACTIVE.start()
        return _ACTIVE


def disable():
    global _ACTIVE
    with _LOCK:
        if _ACTIVE is not None:
            _ACTIVE.stop()
            # stash the beacon map: a later enable() must keep watching
            # the handles components already hold
            with _ACTIVE._lock:
                _CARRIED_BEACONS.update(_ACTIVE._beacons)
        _ACTIVE = None


def active() -> Optional[LivenessMonitor]:
    return _ACTIVE


def beacon(name: str):
    """The per-site handle fetch.  Disabled: one module-global ``None``
    check, then the shared :data:`NOOP_BEACON` by identity."""
    m = _ACTIVE
    if m is None:
        return NOOP_BEACON
    return m.beacon(name)


def state() -> Dict[str, dict]:
    m = _ACTIVE
    if m is None:
        return {}
    return m.state()


def deadline_for(name: str) -> float:
    m = _ACTIVE
    if m is not None:
        return m.deadline_for(name)
    # no monitor: same chain, global default read live from the env
    d = _env_float("PADDLE_TPU_LIVENESS_DEADLINE")
    return _resolve_deadline(name, d if d is not None
                             else DEADLINE_DEFAULT)


# env opt-in: PADDLE_TPU_LIVENESS=1 arms the monitor at import time (the
# flight recorder's env-knob discipline)
if os.environ.get("PADDLE_TPU_LIVENESS", "0") not in ("0", "", "false",
                                                      "off"):
    enable()
