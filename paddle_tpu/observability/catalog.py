"""The metric-name catalog — every metric the framework emits at runtime,
declared once (name, type, labels, unit, help).

This is the observability analogue of ops_schema.yaml: the default
registry refuses undeclared names at fetch time, and
tests/test_observability.py exercises every instrumented subsystem and
asserts the emitted set is covered here — so a dashboard never has to
chase a metric that exists only in source code, and a stale catalog entry
never outlives its instrumentation silently.

Naming: dotted ``<subsystem>.<what>_<unit>`` internally; the Prometheus
exporter rewrites dots to underscores (``serving.ttft_seconds`` ->
``serving_ttft_seconds``).  Label value spaces are bounded by
construction (finish reasons, bucket sizes, declared faultpoint sites,
watchdog entry names).
"""
from __future__ import annotations

__all__ = ["CATALOG"]


def _m(type_, help_, labels=(), unit=""):
    return {"type": type_, "help": help_, "labels": tuple(labels),
            "unit": unit}


CATALOG = {
    # -- serving (engine + continuous-batching scheduler) -------------------
    "serving.ttft_seconds": _m(
        "histogram", "submit -> first token, per finished request "
        "(INCLUDES admission-queue wait; subtract serving.queue_wait_seconds "
        "for pure prefill latency)", unit="seconds"),
    "serving.queue_wait_seconds": _m(
        "histogram", "submit -> admission (prefill start), per request",
        unit="seconds"),
    "serving.tpot_seconds": _m(
        "histogram", "mean seconds per token after the first, per finished "
        "request", unit="seconds"),
    "serving.decode_step_seconds": _m(
        "histogram", "wall time of one batched decode iteration (all slots)",
        unit="seconds"),
    "serving.generated_tokens": _m(
        "counter", "decode tokens appended to live requests (prefill "
        "first-tokens excluded)"),
    "serving.prefill_bucket_hits": _m(
        "counter", "prefill admissions per power-of-two bucket",
        labels=("bucket",)),
    "serving.finished_requests": _m(
        "counter", "retired requests by finish reason",
        labels=("reason",)),
    "serving.slot_occupancy": _m(
        "gauge", "active slots after the latest scheduler iteration"),
    "serving.queue_depth": _m(
        "gauge", "requests waiting for admission"),
    "serving.page_pool_used": _m(
        "gauge", "KV pages currently mapped by any slot (paged cache "
        "occupancy; pool size is engine.num_pages)"),
    "serving.prefix_hit_pages": _m(
        "counter", "prompt pages served from the prefix hash cache at "
        "admission instead of being recomputed/stored"),
    "serving.cow_copies": _m(
        "counter", "copy-on-write page copies (a write targeted a page "
        "shared by another slot)"),
    "serving.prefill_chunk_seconds": _m(
        "histogram", "wall time of one chunked-prefill iteration (one "
        "fixed-size chunk of one admission, interleaved with decode)",
        unit="seconds"),
    "serving.preemptions": _m(
        "counter", "requests evicted under page-pool pressure and "
        "requeued for recompute (vLLM-style preemption; a request "
        "preempted past the scheduler's cap finishes 'cache_full' "
        "instead)"),
    "serving.spec_proposed_tokens": _m(
        "counter", "draft tokens proposed to the speculative verify "
        "step (spec_k per active slot per iteration; pair with "
        "serving.spec_accepted_tokens — accept rate = accepted / "
        "proposed)"),
    "serving.spec_accepted_tokens": _m(
        "counter", "draft tokens the speculative verify step accepted "
        "(the free extra tokens per iteration; the corrective/bonus "
        "sample is not counted)"),
    "serving.kv_quant_error": _m(
        "gauge", "max abs dequantization error of the latest decode/"
        "verify step's int8 KV appends (opt-in: "
        "PADDLE_TPU_METRICS_KV_QUANT_ERROR=1 at engine construction; "
        "forces one device sync per step)"),
    "serving.tp_degree": _m(
        "gauge", "tensor-parallel degree of the most recently "
        "constructed decode engine (1 = single-chip; tp > 1 partitions "
        "the paged KV pool over heads on an ('mp',) mesh)"),
    "serving.collective_bytes": _m(
        "counter", "bytes the sharded decode/verify step's collectives "
        "move over the mesh per iteration, priced once from the "
        "compiled program's partitioned HLO (opt-in: "
        "PADDLE_TPU_METRICS_COLLECTIVES=1 at engine construction; "
        "first step pays one AOT compile for the price)"),

    # -- disaggregated prefill/decode handoff (serving/disagg.py — ISSUE 15)
    "serving.handoff_bytes": _m(
        "counter", "KV bytes moved from a prefill engine's pool into a "
        "decode engine's pool by disaggregated page handoffs (K+V rows "
        "across all layers, int8 scale rows included — kv_row_bytes "
        "truth per transferred page)", unit="bytes"),
    "serving.handoff_seconds": _m(
        "histogram", "wall time of one handoff chunk (export -> stage "
        "-> import of up to handoff_pages pages), interleaved between "
        "decode steps", unit="seconds"),
    "serving.handoff_queue_depth": _m(
        "gauge", "requests queued for or mid KV handoff (the bounded "
        "handoff queue plus in-flight transfers)"),

    # -- tiered KV host cache (serving/kv_tier.py — ISSUE 17) ---------------
    "serving.kv_host_bytes": _m(
        "gauge", "host-RAM page-tier occupancy of the most recent spill/"
        "invalidation (bounded by PADDLE_TPU_KV_HOST_BYTES; 0 = tier "
        "off or empty)", unit="bytes"),
    "serving.kv_host_hits": _m(
        "counter", "host-tier pages pulled back through kv_import and "
        "adopted device-side for an admission that missed the device "
        "prefix cache (a hit is a page that LANDED — torn fetches "
        "count nothing)"),
    "serving.kv_host_misses": _m(
        "counter", "admissions whose prompt had uncovered pages at the "
        "device-coverage boundary and the host tier held none of them "
        "(counted once per admission attempt, not per poll)"),
    "serving.kv_host_spilled_pages": _m(
        "counter", "refcount-0 hash-reachable pages exported to the "
        "host tier (allocator reclaim spills + explicit cold-page "
        "spills)"),
    "serving.kv_tier_fetch_seconds": _m(
        "histogram", "begin -> last page adopted of one host-tier "
        "fetch (interleaved between decode steps; the repeat-prompt "
        "TTFT includes this window)", unit="seconds"),

    # -- replicated serving fleet (serving/router.py — ISSUE 19) ------------
    "router.routed": _m(
        "counter", "admission routing decisions by ladder rung: "
        "affinity (prefix-digest view covered a non-empty prompt "
        "prefix), least_loaded (fresh-snapshot fallback, incl. the "
        "telemetry-blackout round-robin), failover (an orphaned "
        "in-flight request re-placed onto a survivor)",
        labels=("reason",)),
    "router.replicas_healthy": _m(
        "gauge", "replicas currently in the routable set (healthy — "
        "excludes dead, respawn-pending, and joining replicas still "
        "inside their healthy interval)"),
    "router.failovers": _m(
        "counter", "replica deaths the router failed over (crash at "
        "the serve.replica site, stalled step beacon past the "
        "deadline, or a dead thread) — each drains that replica's "
        "in-flight requests onto survivors via recompute requeue"),

    # -- serving front-end (serving/frontend.py — ISSUE 13) -----------------
    "serving.http_requests": _m(
        "counter", "HTTP requests by response status code (200 stream/"
        "complete, 400 bad request, 404, 429 shed over queue_limit, "
        "499 client disconnected mid-stream, 503 draining)",
        labels=("code",)),
    "serving.shed_total": _m(
        "counter", "requests shed by admission control (429 over the "
        "bounded queue + 503 while draining) — the load harness's shed "
        "rate numerator"),
    "serving.open_streams": _m(
        "gauge", "SSE streams currently open (connected clients being "
        "fed tokens)"),
    "serving.goodput_tokens": _m(
        "counter", "generated tokens actually DELIVERED to a connected "
        "client (streamed events that reached the socket, or the token "
        "array of a completed non-streaming response) — the goodput "
        "numerator; tokens computed for a disconnected/cancelled "
        "request never count"),

    # -- training (TrainStep / hapi fit / amp / divergence sentinel) --------
    "train.step_seconds": _m(
        "histogram", "host wall time of one TrainStep call (dispatch; on "
        "async backends completion is not awaited)", unit="seconds"),
    "train.batch_seconds": _m(
        "histogram", "hapi fit per-batch wall time incl. the loss fetch "
        "(a real device sync)", unit="seconds"),
    "train.steps": _m("counter", "TrainStep calls"),
    "train.samples": _m("counter", "leading-dim samples seen by hapi fit"),
    "train.tokens": _m(
        "counter", "batch*seq tokens seen by hapi fit (2-D+ inputs only)"),
    "train.loss": _m("gauge", "last training loss hapi fit observed"),
    "train.grad_norm": _m(
        "gauge", "global gradient norm (opt-in: "
        "PADDLE_TPU_METRICS_GRAD_NORM=1 at TrainStep construction; forces "
        "one device sync per step)"),
    "train.amp_skipped_steps": _m(
        "counter", "optimizer updates the GradScaler skipped on found_inf"),
    "train.divergence_rollbacks": _m(
        "counter", "DivergenceSentinel rewinds to a snapshot"),

    # -- robustness (retry policy, chaos faultpoints) -----------------------
    "robustness.retry_attempts": _m(
        "counter", "retries scheduled by retry_call (first attempts are "
        "not counted; exhaustion raises RetryError)", labels=("op",)),
    "robustness.faultpoint_fires": _m(
        "counter", "injected faults fired by the active FaultPlan",
        labels=("site",)),

    # -- checkpoint ---------------------------------------------------------
    "checkpoint.write_seconds": _m(
        "histogram", "full checkpoint save (serialize + shard write + "
        "manifest + publish)", unit="seconds"),
    "checkpoint.write_bytes": _m(
        "histogram", "bytes per checkpoint save (manifest-intended bytes)",
        unit="bytes"),
    "checkpoint.restore_seconds": _m(
        "histogram", "checkpoint restore (read + verify + deserialize)",
        unit="seconds"),

    # -- tensor-parallel collective-matmul overlap (distributed/mp_overlap —
    # ISSUE 20) --------------------------------------------------------------
    "mp.overlap_chunks": _m(
        "counter", "overlapped collective-matmul islands built at trace "
        "time, valued at the ring chunk count each resolved (the "
        "mp_overlap autotune family's knob; single-hop qkv re-deals "
        "count 1).  Trace-time like compile.count: a compile-once "
        "program contributes once, so a growing value under steady "
        "serving is a retrace leak"),

    # -- kernels / autotune -------------------------------------------------
    "autotune.cache_hits": _m(
        "counter", "resolve() served from pin/memo/persistent cache"),
    "autotune.cache_misses": _m(
        "counter", "resolve() fell through to timed tuning or the "
        "registered default"),
    "autotune.tune_seconds": _m(
        "histogram", "wall time of one timed candidate selection",
        unit="seconds"),

    # -- compile watchdog ---------------------------------------------------
    "compile.count": _m(
        "counter", "XLA compilations per watched jit entry (the recompile "
        "watchdog warns/raises when a compile-once entry exceeds its "
        "budget)", labels=("entry",)),

    # -- liveness watchdog + cluster view (observability.liveness /
    # .aggregate — armed via PADDLE_TPU_LIVENESS=1) -------------------------
    "liveness.stalls": _m(
        "counter", "stalls the liveness monitor fired: a declared "
        "progress beacon with work inflight made no progress past its "
        "deadline (each fire also produced an all-thread-stack flight "
        "dump; label space bounded by the declared beacon registry)",
        labels=("beacon",)),
    "liveness.straggler": _m(
        "gauge", "per-host straggler flag from the host-0 cluster merge "
        "(1 = this host's step-time p50 exceeds the cluster median by "
        "more than PADDLE_TPU_STRAGGLER_PCT percent, 0 = on pace; label "
        "space bounded by world size)", labels=("host",)),

    # -- HBM ledger (observability.hbm — armed via PADDLE_TPU_HBM=1) --------
    "hbm.live_bytes": _m(
        "gauge", "live device bytes per device (summed jax.live_arrays(), "
        "sampled at step/iteration boundaries by the armed ledger; a "
        "sharded array's bytes split evenly across its devices)",
        labels=("device",), unit="bytes"),
    "hbm.kv_pool_bytes": _m(
        "gauge", "summed KV-pool bytes of live serving engines (paged or "
        "slotted, int8-aware: rows * kv_row_bytes() — codes + scales)",
        unit="bytes"),
    "hbm.restore_transient_bytes": _m(
        "gauge", "host-side deserialized checkpoint tree held between "
        "read and device placement (set for the restore's duration, "
        "zero otherwise)", unit="bytes"),
}
