"""The live HBM ledger — who holds device memory, sampled where it's safe.

An OOM post-mortem (or a Perfetto timeline) needs three numbers the
metrics registry didn't carry: **live device bytes** (what jax is
actually holding, per device), **KV-pool bytes** (the serving engines'
dominant allocation — paged or slotted, int8-aware via the engines' own
``kv_row_bytes()`` accounting), and **checkpoint-restore transients**
(the host-side deserialized tree that exists between read and device
placement).  This module owns all three as catalog'd gauges:

* ``hbm.live_bytes{device=}`` — ``sum(a.nbytes)`` over
  ``jax.live_arrays()``, per device (a sharded array's bytes split
  evenly across its devices — a per-shard approximation, documented);
* ``hbm.kv_pool_bytes`` — summed ``kv_pool_bytes()`` over live
  registered engines;
* ``hbm.restore_transient_bytes`` — set for the duration of a
  checkpoint restore, zero otherwise.

**Sampling discipline** (the registry's): the ledger is OFF by default —
:func:`maybe_sample` is one module-global ``None`` check
(test-asserted), so the scheduler's per-iteration call and hapi fit's
per-batch call cost nothing unless armed via ``PADDLE_TPU_HBM=1`` or
:func:`enable`.  Samples run at **step/iteration boundaries on the
host, never inside a trace**: ``jax.live_arrays()`` enumerates the
runtime's buffers (meaningless under tracing) and the gauges' own
``float()`` guard rejects tracers anyway.  ``PADDLE_TPU_HBM_EVERY=N``
thins armed sampling to every N-th boundary.

Every sample also appends **counter marks** ``(name, perf_ns, value)``
to a bounded ring; :func:`paddle_tpu.observability.tracing.write_chrome`
merges them as chrome-trace ``"C"`` events, so Perfetto shows HBM
occupancy time-aligned with the request lanes and profiler spans.
Flight-recorder dumps call :func:`ledger_state` (works armed or not —
dump time is exactly when an unarmed process wants a fresh collection)
to embed the per-device totals plus a **top-arrays breakdown**
(aggregated by shape/dtype) — the "what held the memory" answer.
"""
from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from . import registry as _registry

__all__ = [
    "HbmLedger", "enable", "disable", "active", "maybe_sample", "sample",
    "register_engine", "note_restore", "clear_restore", "ledger_state",
    "counter_marks", "MARKS_CAP", "TOP_ARRAYS",
]

#: bound on buffered chrome counter marks (drop-oldest past it)
MARKS_CAP = 4096

#: entries in the dump-time largest-live-arrays breakdown
TOP_ARRAYS = 15

#: live engines whose KV pools the ledger prices; module-level weakset so
#: engines built before enable() are covered (flight-recorder pattern)
_ENGINES: "weakref.WeakSet" = weakref.WeakSet()

_ACTIVE: Optional["HbmLedger"] = None
_LOCK = threading.Lock()


def _live_per_device() -> Dict[str, float]:
    """{device string: live bytes} over ``jax.live_arrays()``.  A sharded
    array's bytes are split evenly across its devices (per-shard
    approximation: jax reports the logical nbytes).  Deleted/torn arrays
    are skipped — a mid-crash collection must not raise."""
    import jax
    per: Dict[str, float] = {}
    for a in jax.live_arrays():
        try:
            devs = list(a.devices())
            nb = float(a.nbytes)
        except Exception:
            continue
        if not devs:
            continue
        share = nb / len(devs)
        for d in devs:
            key = str(d)
            per[key] = per.get(key, 0.0) + share
    return per


def _top_arrays(n: int = TOP_ARRAYS) -> List[Dict[str, Any]]:
    """The largest live allocations aggregated by (shape, dtype) — the
    post-mortem's "what held the memory" table."""
    import jax
    agg: Dict[tuple, List[float]] = {}
    for a in jax.live_arrays():
        try:
            key = (str(tuple(a.shape)), str(a.dtype))
            nb = float(a.nbytes)
        except Exception:
            continue
        ent = agg.setdefault(key, [0.0, 0])
        ent[0] += nb
        ent[1] += 1
    rows = sorted(((b, c, k) for k, (b, c) in agg.items()), reverse=True)
    return [{"shape": k[0], "dtype": k[1], "nbytes": int(b), "count": c}
            for b, c, k in rows[:n]]


def _kv_pool_total() -> float:
    total = 0.0
    for e in list(_ENGINES):
        try:
            total += float(e.kv_pool_bytes())
        except Exception:
            continue
    return total


def _kv_host_total() -> float:
    """Summed host-RAM KV tier occupancy of live serving engines (ISSUE
    17) — the ledger's host-side row next to the device pool's, so one
    flight dump shows where every cached KV byte lives."""
    total = 0.0
    for e in list(_ENGINES):
        try:
            total += float(e.kv_host_bytes_used())
        except Exception:
            continue
    return total


class HbmLedger:
    """The armed ledger: gauges + the chrome counter-mark ring."""

    def __init__(self, sample_every: Optional[int] = None):
        every = (sample_every if sample_every is not None
                 else int(os.environ.get("PADDLE_TPU_HBM_EVERY", "1")))
        self.sample_every = max(int(every), 1)
        self._n = 0
        self._lock = threading.Lock()
        self._marks: deque = deque(maxlen=MARKS_CAP)
        self._g_live = _registry.gauge("hbm.live_bytes", ("device",))
        self._g_kv = _registry.gauge("hbm.kv_pool_bytes")
        self._seen_devices: set = set()
        self.last: Dict[str, Any] = {}

    def _mark(self, name: str, ts_ns: int, value: float):
        with self._lock:
            self._marks.append((name, ts_ns, float(value)))

    def sample(self, tag: str = "") -> Dict[str, Any]:
        """One full collection: set the gauges, append counter marks,
        remember the sample.  Host-side only — call at step/iteration
        boundaries, never inside a trace."""
        ts_ns = time.perf_counter_ns()
        per = _live_per_device()
        # a device that dropped out of the collection (its arrays were
        # all deleted) must read 0, not its last value — a stale gauge
        # would contradict ledger_state() in the exact OOM post-mortem
        # this module exists for
        for dev in self._seen_devices - set(per):
            self._g_live.labels(device=dev).set(0.0)
            self._mark("hbm.live_bytes{device=%s}" % dev, ts_ns, 0.0)
        self._seen_devices = set(per)
        for dev, nbytes in per.items():
            self._g_live.labels(device=dev).set(nbytes)
            self._mark("hbm.live_bytes{device=%s}" % dev, ts_ns, nbytes)
        kv = _kv_pool_total()
        self._g_kv.set(kv)
        self._mark("hbm.kv_pool_bytes", ts_ns, kv)
        self.last = {"ts_ns": ts_ns, "tag": tag, "devices": per,
                     "kv_pool_bytes": kv,
                     "live_bytes_total": sum(per.values())}
        return self.last

    def maybe_sample(self, tag: str = ""):
        self._n += 1
        if self._n % self.sample_every:
            return None
        return self.sample(tag)

    def marks(self) -> List[tuple]:
        with self._lock:
            return list(self._marks)


# ---------------------------------------------------------------------------
# module-level API (what the instrumented subsystems call)
# ---------------------------------------------------------------------------

def enable(sample_every: Optional[int] = None) -> HbmLedger:
    """Arm (or re-arm) the process-wide ledger."""
    global _ACTIVE
    with _LOCK:
        _ACTIVE = HbmLedger(sample_every=sample_every)
        return _ACTIVE


def disable():
    global _ACTIVE
    with _LOCK:
        _ACTIVE = None


def active() -> Optional[HbmLedger]:
    return _ACTIVE


def maybe_sample(tag: str = ""):
    """Per-boundary hook: ONE module-global ``None`` check when the
    ledger is disarmed (the default) — the scheduler/fit hot loops pay
    nothing (test-asserted, registry noop-identity discipline)."""
    led = _ACTIVE
    if led is None:
        return None
    return led.maybe_sample(tag)


def sample(tag: str = ""):
    led = _ACTIVE
    if led is None:
        return None
    return led.sample(tag)


def register_engine(engine):
    """Track a serving engine (weakref) whose ``kv_pool_bytes()`` the
    ledger prices.  Always cheap; engines register at construction."""
    _ENGINES.add(engine)


def counter_marks() -> List[tuple]:
    """Buffered ``(name, perf_ns, value)`` marks for the chrome-trace
    exporter's HBM counter lanes; [] while disarmed."""
    led = _ACTIVE
    return led.marks() if led is not None else []


def note_restore(nbytes: int):
    """Checkpoint restore began: record the transient host-side tree
    size.  Sets the gauge regardless of arming (restores are cold path;
    the gauge no-ops itself when metrics are off)."""
    _registry.gauge("hbm.restore_transient_bytes").set(float(nbytes))
    led = _ACTIVE
    if led is not None:
        led._mark("hbm.restore_transient_bytes",
                  time.perf_counter_ns(), float(nbytes))


def clear_restore():
    _registry.gauge("hbm.restore_transient_bytes").set(0.0)
    led = _ACTIVE
    if led is not None:
        led._mark("hbm.restore_transient_bytes",
                  time.perf_counter_ns(), 0.0)


def ledger_state(top_n: int = TOP_ARRAYS) -> Dict[str, Any]:
    """JSON-ready ledger snapshot for flight dumps: a FRESH collection
    (works armed or not — the dump moment is exactly when an unarmed
    process wants one) plus the last periodic sample when armed.  Never
    raises — a broken collection must not mask the fault being dumped."""
    out: Dict[str, Any] = {"armed": _ACTIVE is not None}
    try:
        per = _live_per_device()
        out["devices"] = per
        out["live_bytes_total"] = sum(per.values())
        out["top_arrays"] = _top_arrays(top_n)
        out["kv_pool_bytes"] = _kv_pool_total()
        out["kv_host_bytes"] = _kv_host_total()
    except Exception as e:
        out["error"] = repr(e)
    led = _ACTIVE
    if led is not None and led.last:
        out["last_sample"] = dict(led.last)
    return out


# env opt-in: PADDLE_TPU_HBM=1 arms the ledger at import time (the
# registry's env-knob discipline; PADDLE_TPU_HBM_EVERY thins sampling)
if os.environ.get("PADDLE_TPU_HBM", "0") not in ("0", "", "false", "off"):
    enable()
