"""Data pipeline (reference surface: python/paddle/io/ + fluid/dataloader/).

TPU-native DataLoader: worker processes (or threads) produce numpy batches,
a prefetcher overlaps host->device transfer with compute (the role the
reference's pin-memory + C++ reader queues played,
paddle/fluid/pybind/reader_py.cc, paddle/fluid/operators/reader/).
"""
from __future__ import annotations

import itertools
import math
import queue as _queue
import threading
from typing import Iterable, List, Optional, Sequence

import numpy as np

from ..core import random as _rnd
from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        di = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if di == 0 else int(self.cum[di - 1])
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        counts = [int(math.floor(n * f)) for f in lengths]
        counts[-1] = n - sum(counts[:-1])
        lengths = counts
    perm = np.random.RandomState(
        _rnd.default_generator().initial_seed or None).permutation(
        len(dataset)).tolist()
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l]))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.default_rng()
        if self.replacement:
            return iter(rng.integers(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.default_rng()
        return iter(rng.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Per-rank disjoint shard of the dataset
    (reference: python/paddle/io/dataloader/batch_sampler.py
    DistributedBatchSampler) — on TPU this shards by process index for
    multi-host input pipelines."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None:
            try:
                import jax
                num_replicas = jax.process_count()
            except Exception:
                num_replicas = 1
        if rank is None:
            try:
                import jax
                rank = jax.process_index()
            except Exception:
                rank = 0
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / num_replicas))
        self.total_size = self.num_samples * num_replicas

    def __iter__(self):
        indices = list(range(len(self.dataset)))
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices += indices[: self.total_size - len(indices)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (reference:
    fluid/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._array) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return tuple(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class DataLoader:
    """reference surface: python/paddle/io/DataLoader (fluid/reader.py:146).

    num_workers>0 uses a thread pool producing ready batches ahead of time
    (numpy work releases the GIL; the heavy lifting is in the dataset's own
    decode code), plus a device-prefetch queue.
    """

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.prefetch_factor = max(prefetch_factor, 2)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _iter_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._iter_batches()
            return
        if (self.use_shared_memory and not self._iterable_mode):
            it = self._iter_multiprocess()
            if it is not None:
                yield from it
                return
        yield from self._iter_threaded()

    def _iter_threaded(self):
        q: _queue.Queue = _queue.Queue(maxsize=self.prefetch_factor
                                       * self.num_workers)
        sentinel = object()

        def producer():
            try:
                for b in self._iter_batches():
                    q.put(b)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True,
                             name="dataloader-producer")
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item

    def _iter_multiprocess(self):
        """Real worker processes over the native shared-memory ring queue
        (csrc/shm_queue.cpp) — the C++ data-feed path.  Returns None when
        the native transport is unavailable (caller falls back to threads).
        """
        try:
            from .shm_queue import ShmQueue
            out_q = ShmQueue(capacity=128 << 20)
        except Exception:
            return None
        import multiprocessing as mp
        ctx = mp.get_context("fork")
        all_batches = list(self.batch_sampler)
        nw = min(self.num_workers, max(len(all_batches), 1))
        dataset = self.dataset
        collate = self.collate_fn
        init_fn = self.worker_init_fn
        qname = out_q.name

        def worker(wid):
            from .shm_queue import ShmQueue as SQ
            q = SQ(qname, create=False)
            if init_fn is not None:
                init_fn(wid)
            for bi in range(wid, len(all_batches), nw):
                idxs = all_batches[bi]
                batch = collate([dataset[i] for i in idxs])
                import numpy as _np
                from ..core.tensor import Tensor as _T
                import jax.tree_util as jtu
                payload = jtu.tree_map(
                    lambda t: _np.asarray(t._array) if isinstance(t, _T) else t,
                    batch, is_leaf=lambda l: isinstance(l, _T))
                q.put((bi, payload))
            q.put(("done", wid))

        procs = [ctx.Process(target=worker, args=(w,), daemon=True)
                 for w in range(nw)]
        for p in procs:
            p.start()

        def gen():
            from ..core.tensor import Tensor as _T
            import jax.tree_util as jtu
            pending = {}
            done = 0
            nxt = 0
            total = len(all_batches)
            try:
                while nxt < total:
                    if nxt in pending:
                        payload = pending.pop(nxt)
                    else:
                        tag, payload_or_wid = out_q.get()
                        if tag == "done":
                            done += 1
                            if done == nw and nxt >= total:
                                break
                            continue
                        if tag != nxt:
                            pending[tag] = payload_or_wid
                            continue
                        payload = payload_or_wid
                    nxt += 1
                    yield jtu.tree_map(
                        lambda a: _T(a) if hasattr(a, "dtype") else a, payload)
            finally:
                out_q.close()
                for p in procs:
                    p.join(timeout=2)
                    if p.is_alive():
                        p.terminate()
                out_q.destroy()

        return gen()
