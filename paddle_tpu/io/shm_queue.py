"""Shared-memory queue — Python interface over csrc/shm_queue.cpp.

The native transport for multiprocess DataLoader workers (reference
analogue: fluid/dataloader shared-memory mmap tensors + the C++
BlockingQueue behind pybind/reader_py.cc).
"""
from __future__ import annotations

import ctypes
import os
import pickle
import uuid

from ..core import native as _native


class ShmQueue:
    def __init__(self, name: str = None, capacity: int = 64 << 20,
                 create: bool = True):
        lib = _native.load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.name = name or f"/ptq_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        if create:
            self._q = lib.shm_queue_create(self.name.encode(), capacity)
        else:
            self._q = lib.shm_queue_open(self.name.encode())
        if not self._q:
            raise RuntimeError(f"shm_queue init failed for {self.name}")
        self._owner = create

    def open_in_child(self):
        """Re-open the mapping after fork/spawn (handle is per-process)."""
        return ShmQueue(self.name, create=False)

    def put(self, obj):
        data = pickle.dumps(obj, protocol=4)
        rc = self._lib.shm_queue_push(self._q, data, len(data))
        if rc == -2:
            raise ValueError(f"item of {len(data)} bytes exceeds queue capacity")
        if rc != 0:
            raise RuntimeError("queue closed")

    def get(self, max_bytes: int = 256 << 20):
        cap = 1 << 20
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.shm_queue_pop(self._q, buf, cap)
            if n == -3:
                cap = min(cap * 4, max_bytes)
                continue
            if n < 0:
                raise EOFError("queue closed")
            return pickle.loads(buf.raw[:n])

    def qsize(self):
        return int(self._lib.shm_queue_size(self._q))

    def close(self):
        if self._q:
            self._lib.shm_queue_close(self._q)

    def destroy(self):
        if self._q:
            self._lib.shm_queue_destroy(self._q)
            self._q = None

    def __getstate__(self):
        return {"name": self.name}

    def __setstate__(self, state):
        fresh = ShmQueue(state["name"], create=False)
        self.__dict__.update(fresh.__dict__)
        self._owner = False
