"""paddle.regularizer — L1Decay / L2Decay (reference:
python/paddle/regularizer.py:20 L1Decay, :82 L2Decay over
fluid/regularizer.py L1DecayRegularizer/L2DecayRegularizer).

Accepted by ``optimizer(weight_decay=...)``: L2Decay adds ``coeff * p`` to
the gradient (coupled decay, the reference's append_regularization_ops
semantics); L1Decay adds ``coeff * sign(p)``.  AdamW keeps its decoupled
decay for float/L2Decay coefficients.
"""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    _mode = "l2"

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
        self._coeff = float(coeff)  # legacy alias read by Optimizer._coeff

    def __repr__(self):
        return "%s(coeff=%g)" % (type(self).__name__, self.coeff)


class L1Decay(WeightDecayRegularizer):
    r"""loss += coeff * sum(|p|)  =>  grad += coeff * sign(p)."""
    _mode = "l1"


class L2Decay(WeightDecayRegularizer):
    r"""loss += 0.5 * coeff * sum(p^2)  =>  grad += coeff * p."""
    _mode = "l2"
