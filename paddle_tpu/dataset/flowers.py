"""Flowers-102 reader creators (reference dataset/flowers.py)."""
from ..vision.datasets import Flowers
from ._factory import reader_from

__all__ = ["train", "test", "valid"]


def train(**kw):
    return reader_from(Flowers, "train", **kw)


def test(**kw):
    return reader_from(Flowers, "test", **kw)


def valid(**kw):
    return reader_from(Flowers, "valid", **kw)
