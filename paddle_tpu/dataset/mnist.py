"""MNIST reader creators (reference dataset/mnist.py)."""
from ..vision.datasets import MNIST
from ._factory import reader_from

__all__ = ["train", "test"]


def train(image_path=None, label_path=None, **kw):
    return reader_from(MNIST, "train", image_path=image_path,
                       label_path=label_path, **kw)


def test(image_path=None, label_path=None, **kw):
    return reader_from(MNIST, "test", image_path=image_path,
                       label_path=label_path, **kw)
