"""UCI housing reader creators (reference dataset/uci_housing.py)."""
from ..text import UCIHousing
from ._factory import reader_from

__all__ = ["train", "test"]


def train(**kw):
    return reader_from(UCIHousing, "train", **kw)


def test(**kw):
    return reader_from(UCIHousing, "test", **kw)
