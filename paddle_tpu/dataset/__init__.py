"""paddle.dataset — legacy reader-protocol dataset creators (reference:
python/paddle/dataset/__init__.py).  Each submodule exposes train()/test()
reader creators (zero-arg callables yielding samples) wrapping the modern
class-based datasets in paddle_tpu.vision.datasets / paddle_tpu.text —
same on-disk formats, legacy feeding protocol."""
from . import common  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import flowers  # noqa: F401
from . import voc2012  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import movielens  # noqa: F401
from . import uci_housing  # noqa: F401
from . import conll05  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401

__all__ = ["common", "mnist", "cifar", "flowers", "voc2012", "imdb",
           "imikolov", "movielens", "uci_housing", "conll05", "wmt14",
           "wmt16"]
