"""paddle.dataset.common (reference dataset/common.py): md5file and the
cache-home convention.  download() needs network egress, which this build
does not have — it raises with the local-path recipe instead."""
import hashlib
import os

__all__ = ["DATA_HOME", "md5file", "download"]

DATA_HOME = os.path.expanduser("~/.cache/paddle/dataset")


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    raise RuntimeError(
        "paddle.dataset download requires network access, which this "
        "build does not have. Place the archive under %s/%s and pass its "
        "path to the dataset constructor." % (DATA_HOME, module_name))
