"""CoNLL-2005 SRL reader creators (reference dataset/conll05.py)."""
from ..text import Conll05st
from ._factory import reader_from

__all__ = ["test"]


def test(**kw):
    # the reference ships only the public test split (conll05.py:24)
    return reader_from(Conll05st, "test", **kw)
