"""VOC2012 segmentation reader creators (reference dataset/voc2012.py)."""
from ..vision.datasets import VOC2012
from ._factory import reader_from

__all__ = ["train", "test", "val"]


def train(**kw):
    return reader_from(VOC2012, "train", **kw)


def test(**kw):
    return reader_from(VOC2012, "test", **kw)


def val(**kw):
    return reader_from(VOC2012, "valid", **kw)
