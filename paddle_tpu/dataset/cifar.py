"""CIFAR reader creators (reference dataset/cifar.py)."""
from ..vision.datasets import Cifar10, Cifar100
from ._factory import reader_from

__all__ = ["train10", "test10", "train100", "test100"]


def train10(data_file=None, **kw):
    return reader_from(Cifar10, "train", data_file=data_file, **kw)


def test10(data_file=None, **kw):
    return reader_from(Cifar10, "test", data_file=data_file, **kw)


def train100(data_file=None, **kw):
    return reader_from(Cifar100, "train", data_file=data_file, **kw)


def test100(data_file=None, **kw):
    return reader_from(Cifar100, "test", data_file=data_file, **kw)
