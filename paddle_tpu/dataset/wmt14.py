"""WMT14 en-fr reader creators (reference dataset/wmt14.py)."""
from ..text import WMT14
from ._factory import reader_from

__all__ = ["train", "test"]


def train(dict_size=-1, **kw):
    return reader_from(WMT14, "train", **kw)


def test(dict_size=-1, **kw):
    return reader_from(WMT14, "test", **kw)
