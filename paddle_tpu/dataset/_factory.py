"""Shared reader-creator factory for the legacy dataset modules."""
from __future__ import annotations


def reader_from(cls, mode, **kw):
    """Wrap a class-based Dataset into a legacy reader creator."""
    def reader():
        ds = cls(mode=mode, **kw)
        for i in range(len(ds)):
            yield ds[i]
    return reader
