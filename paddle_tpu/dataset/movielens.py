"""MovieLens reader creators (reference dataset/movielens.py)."""
from ..text import Movielens
from ._factory import reader_from

__all__ = ["train", "test"]


def train(**kw):
    return reader_from(Movielens, "train", **kw)


def test(**kw):
    return reader_from(Movielens, "test", **kw)
