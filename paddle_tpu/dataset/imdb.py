"""IMDB sentiment reader creators (reference dataset/imdb.py)."""
from ..text import Imdb
from ._factory import reader_from

__all__ = ["train", "test"]


def train(word_idx=None, **kw):
    return reader_from(Imdb, "train", **kw)


def test(word_idx=None, **kw):
    return reader_from(Imdb, "test", **kw)
