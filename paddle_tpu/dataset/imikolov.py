"""PTB/imikolov LM reader creators (reference dataset/imikolov.py)."""
from ..text import Imikolov
from ._factory import reader_from

__all__ = ["train", "test"]


def train(word_idx=None, n=5, **kw):
    return reader_from(Imikolov, "train", window_size=n, **kw)


def test(word_idx=None, n=5, **kw):
    return reader_from(Imikolov, "test", window_size=n, **kw)
