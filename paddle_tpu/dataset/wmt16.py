"""WMT16 multimodal en-de reader creators (reference dataset/wmt16.py)."""
from ..text import WMT16
from ._factory import reader_from

__all__ = ["train", "test", "validation"]


def train(src_dict_size=-1, trg_dict_size=-1, **kw):
    return reader_from(WMT16, "train", **kw)


def test(src_dict_size=-1, trg_dict_size=-1, **kw):
    return reader_from(WMT16, "test", **kw)


def validation(src_dict_size=-1, trg_dict_size=-1, **kw):
    return reader_from(WMT16, "val", **kw)
