"""paddle.compat — string/number compatibility helpers (reference:
python/paddle/compat.py: to_text:25, to_bytes:121, round:206,
floor_division:232, get_exception_message:249)."""
from __future__ import annotations

import math

__all__ = ["to_text", "to_bytes", "round", "floor_division",
           "get_exception_message"]

_builtin_round = round


def to_text(obj, encoding="utf-8", inplace=False):
    """Decode bytes (recursively through list/set/dict) to str."""
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_to_text(o, encoding) for o in obj]
            return obj
        return [_to_text(o, encoding) for o in obj]
    if isinstance(obj, set):
        if inplace:
            items = [_to_text(o, encoding) for o in obj]
            obj.clear()
            obj.update(items)
            return obj
        return {_to_text(o, encoding) for o in obj}
    if isinstance(obj, dict):
        if inplace:
            new = {_to_text(k, encoding): _to_text(v, encoding)
                   for k, v in obj.items()}
            obj.clear()
            obj.update(new)
            return obj
        return {_to_text(k, encoding): _to_text(v, encoding)
                for k, v in obj.items()}
    return _to_text(obj, encoding)


def _to_text(obj, encoding):
    if obj is None:
        return obj
    if isinstance(obj, (bytes, bytearray)):
        return bytes(obj).decode(encoding)
    if isinstance(obj, str):
        return obj
    return str(obj)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """Encode str (recursively through list/set) to bytes."""
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_to_bytes(o, encoding) for o in obj]
            return obj
        return [_to_bytes(o, encoding) for o in obj]
    if isinstance(obj, set):
        if inplace:
            items = [_to_bytes(o, encoding) for o in obj]
            obj.clear()
            obj.update(items)
            return obj
        return {_to_bytes(o, encoding) for o in obj}
    return _to_bytes(obj, encoding)


def _to_bytes(obj, encoding):
    if obj is None:
        return obj
    if isinstance(obj, str):
        return obj.encode(encoding)
    if isinstance(obj, (bytes, bytearray)):
        return bytes(obj)
    return str(obj).encode(encoding)


def round(x, d=0):
    """Python2-style half-away-from-zero rounding."""
    if x == float("inf") or x == -float("inf") or x != x:  # inf/nan
        return x
    p = 10 ** d
    if x >= 0.0:
        return float(math.floor((x * p) + math.copysign(0.5, x))) / p
    return float(math.ceil((x * p) + math.copysign(0.5, x))) / p


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    if exc is None:
        raise ValueError("exc should not be None")
    return str(exc)
