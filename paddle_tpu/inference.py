"""paddle.inference — the deployment predictor API (reference:
python/paddle/inference/__init__.py over
fluid/inference/api/paddle_inference_api.h: Config, Predictor,
create_predictor, get_version).

The engine is the exported StableHLO artifact (static.load_inference_model
/ SURVEY §2.1 N27); Config points at the same two-file prefix the
reference's (prog_file, params_file) pair uses."""
from __future__ import annotations

import enum
import os

from . import __version__ as _version
from .static import load_inference_model

__all__ = ["Config", "DataType", "PlaceType", "PrecisionType", "Tensor",
           "Predictor", "create_predictor", "get_version"]


class DataType(enum.Enum):
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


class PlaceType(enum.Enum):
    kUNK = -1
    kCPU = 0
    kGPU = 1
    kXPU = 2
    kNPU = 3
    kIPU = 4
    kTPU = 5


class PrecisionType(enum.Enum):
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class Config:
    """Predictor configuration (reference paddle_analysis_config.h).  The
    artifact prefix comes from ``prog_file`` minus its extension (both
    artifact files share the prefix)."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is None:
            raise ValueError("Config needs the exported artifact: "
                             "Config('<prefix>.pdmodel', "
                             "'<prefix>.pdiparams')")
        self._prog_file = prog_file
        self._params_file = params_file
        self._prefix = (prog_file[:-len(".pdmodel")]
                        if prog_file.endswith(".pdmodel") else prog_file)

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    # accepted-and-ignored knobs (XLA owns placement/precision here; kept
    # so reference deployment scripts run unchanged)
    def enable_use_gpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def set_cpu_math_library_num_threads(self, *a, **k):
        pass

    def switch_ir_optim(self, *a, **k):
        pass

    def enable_memory_optim(self, *a, **k):
        pass


class Tensor:
    """Named handle mirroring the reference's ZeroCopyTensor flow."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, data):
        self._value = data

    def copy_to_cpu(self):
        import numpy as np
        return np.asarray(self._value)

    def shape(self):
        return list(getattr(self._value, "shape", ()))


class Predictor:
    """reference Predictor (paddle_inference_api.h): named-handle feed /
    run / named-handle fetch over the loaded artifact."""

    def __init__(self, config: Config):
        if not os.path.exists(config._prefix + ".pdiparams"):
            raise FileNotFoundError(
                "no artifact at prefix %r (expected .pdiparams/.pdmodel "
                "from static.save_inference_model)" % (config._prefix,))
        self._impl = load_inference_model(config._prefix)
        self._inputs = {n: Tensor(n) for n in self._impl.feed_names}
        self._outputs = {n: Tensor(n) for n in self._impl.fetch_names}

    def get_input_names(self):
        return list(self._impl.feed_names)

    def get_output_names(self):
        return list(self._impl.fetch_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self):
        feeds = [self._inputs[n]._value for n in self._impl.feed_names]
        outs = self._impl.run(feeds)
        names = self._impl.fetch_names or [
            "fetch_%d" % i for i in range(len(outs))]
        for n, o in zip(names, outs):
            self._outputs.setdefault(n, Tensor(n))._value = o.numpy()
        return True


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def get_version():
    return _version
