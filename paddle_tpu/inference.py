"""paddle.inference — the deployment predictor API (reference:
python/paddle/inference/__init__.py over
fluid/inference/api/paddle_inference_api.h: Config, Predictor,
create_predictor, get_version).

The engine is the exported StableHLO artifact (static.load_inference_model
/ SURVEY §2.1 N27); Config points at the same two-file prefix the
reference's (prog_file, params_file) pair uses."""
from __future__ import annotations

import enum
import os

from . import __version__ as _version
from .static import load_inference_model

__all__ = ["Config", "DataType", "PlaceType", "PrecisionType", "Tensor",
           "Predictor", "create_predictor", "get_version"]


class DataType(enum.Enum):
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


class PlaceType(enum.Enum):
    kUNK = -1
    kCPU = 0
    kGPU = 1
    kXPU = 2
    kNPU = 3
    kIPU = 4
    kTPU = 5


class PrecisionType(enum.Enum):
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class Config:
    """Predictor configuration (reference paddle_analysis_config.h).  The
    artifact prefix comes from ``prog_file`` minus its extension (both
    artifact files share the prefix)."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is None:
            raise ValueError("Config needs the exported artifact: "
                             "Config('<prefix>.pdmodel', "
                             "'<prefix>.pdiparams')")
        self._prog_file = prog_file
        self._params_file = params_file
        self._prefix = (prog_file[:-len(".pdmodel")]
                        if prog_file.endswith(".pdmodel") else prog_file)

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    # accepted-and-ignored knobs (XLA owns placement/precision here; kept
    # so reference deployment scripts run unchanged)
    def enable_use_gpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def set_cpu_math_library_num_threads(self, *a, **k):
        pass

    def switch_ir_optim(self, *a, **k):
        pass

    def enable_memory_optim(self, *a, **k):
        pass


class Tensor:
    """Named handle mirroring the reference's ZeroCopyTensor flow."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, data):
        self._value = data

    def copy_to_cpu(self):
        import numpy as np
        return np.asarray(self._value)

    def shape(self):
        return list(getattr(self._value, "shape", ()))


class Predictor:
    """reference Predictor (paddle_inference_api.h): named-handle feed /
    run / named-handle fetch over the loaded artifact.

    A predictor may alternatively be MODEL-BACKED (``create_predictor(
    model=layer)``): instead of a fixed-shape exported artifact it holds a
    live causal-LM Layer, and :meth:`generate` serves it through the
    decode engine (static slotted KV cache + continuous batching —
    SERVING.md)."""

    def __init__(self, config: Config = None, model=None):
        self._layer = model
        if model is not None:
            self._impl = None
            self._inputs, self._outputs = {}, {}
            return
        if config is None:
            raise ValueError("Predictor needs a Config (artifact-backed) "
                             "or model= (serving-engine-backed)")
        if not os.path.exists(config._prefix + ".pdiparams"):
            raise FileNotFoundError(
                "no artifact at prefix %r (expected .pdiparams/.pdmodel "
                "from static.save_inference_model)" % (config._prefix,))
        self._impl = load_inference_model(config._prefix)
        self._inputs = {n: Tensor(n) for n in self._impl.feed_names}
        self._outputs = {n: Tensor(n) for n in self._impl.fetch_names}

    def _require_artifact(self, what):
        if self._impl is None:
            raise RuntimeError(
                "%s needs an artifact-backed predictor; this one wraps a "
                "live model — use generate(...)" % (what,))

    def get_input_names(self):
        self._require_artifact("get_input_names()")
        return list(self._impl.feed_names)

    def get_output_names(self):
        self._require_artifact("get_output_names()")
        return list(self._impl.fetch_names)

    def get_input_handle(self, name):
        self._require_artifact("get_input_handle()")
        return self._inputs[name]

    def get_output_handle(self, name):
        self._require_artifact("get_output_handle()")
        return self._outputs[name]

    def run(self):
        self._require_artifact("run()")
        feeds = [self._inputs[n]._value for n in self._impl.feed_names]
        outs = self._impl.run(feeds)
        names = self._impl.fetch_names or [
            "fetch_%d" % i for i in range(len(outs))]
        for n, o in zip(names, outs):
            self._outputs.setdefault(n, Tensor(n))._value = o.numpy()
        return True

    def generate(self, input_ids, max_new_tokens=20, temperature=1.0,
                 top_k=0, top_p=1.0, eos_token_id=None, seed=0,
                 num_slots=None, max_len=None):
        """Serve autoregressive generation through the decode engine
        (static slotted KV cache + continuous batching; the decode step
        compiles once for the life of the predictor — SERVING.md).

        ``input_ids``: 2-D int array of prompts, or a ragged list of 1-D
        prompts.  Returns a list of 1-D int32 np arrays (generated ids,
        prompts excluded), in input order."""
        if self._layer is None:
            raise NotImplementedError(
                "generate() needs a model-backed predictor "
                "(create_predictor(model=layer)): the exported StableHLO "
                "artifact is fixed-shape and cannot host the slotted "
                "decode loop — re-create the predictor from the Layer, "
                "or run the engine directly (paddle_tpu.serving.generate)")
        from .serving import generate as _generate
        return _generate(self._layer, input_ids,
                         max_new_tokens=max_new_tokens,
                         temperature=temperature, top_k=top_k, top_p=top_p,
                         eos_token_id=eos_token_id, seed=seed,
                         num_slots=num_slots, max_len=max_len)


def create_predictor(config: Config = None, model=None) -> Predictor:
    return Predictor(config, model=model)


def get_version():
    return _version
