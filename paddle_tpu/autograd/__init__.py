"""paddle_tpu.autograd (reference surface: python/paddle/autograd/).

Two layers:
* eager-tape utilities: ``backward``, ``PyLayer`` (custom autograd node —
  reference: paddle/fluid/eager/pylayer/, python/paddle/autograd/py_layer.py)
* functional transforms delegating to jax: ``vjp``, ``jvp``, ``Jacobian``,
  ``Hessian`` (reference: python/paddle/autograd/functional.py:22,:79,:165)
  — these run on raw-fn semantics, supporting arbitrary-order composition,
  which the reference could not do.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core.dispatch import call, unwrap
from ..core.engine import grad, run_backward
from ..core.grad_mode import no_grad
from ..core.tensor import GradNode, Tensor

__all__ = ["backward", "grad", "PyLayer", "PyLayerContext", "vjp", "jvp",
           "Jacobian", "Hessian", "no_grad"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    run_backward(list(tensors), list(grad_tensors), retain_graph=retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.non_differentiable = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_non_differentiable(self, *tensors):
        self.non_differentiable = tensors


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """User-defined autograd op.

    class Exp(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = paddle_tpu.exp(x)
            ctx.save_for_backward(y)
            return y
        @staticmethod
        def backward(ctx, dy):
            y, = ctx.saved_tensor
            return dy * y
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(out, (tuple, list))
        outs = (out,) if single else tuple(out)

        diff_inputs = [a for a in args
                       if isinstance(a, Tensor) and not a.stop_gradient]
        from ..core.grad_mode import is_grad_enabled
        if diff_inputs and is_grad_enabled():
            cls_ref = cls

            def vjp_fn(cots):
                if not isinstance(cots, (tuple, list)):
                    cots = (cots,)
                grads = cls_ref.backward(
                    ctx, *[Tensor(c) for c in cots])
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                # backward returns one grad per *tensor* forward input, in
                # order; pick out the ones for differentiable inputs
                out = []
                ti = 0
                for a in args:
                    if isinstance(a, Tensor):
                        if not a.stop_gradient:
                            g = grads[ti] if ti < len(grads) else None
                            out.append(g._array if isinstance(g, Tensor) else g)
                        ti += 1
                return tuple(out)

            node = GradNode(
                vjp_fn=vjp_fn,
                inputs=diff_inputs,
                out_avals=[(tuple(o._array.shape), o._array.dtype)
                           for o in outs],
                name=cls.__name__,
                out_treedef=jax.tree_util.tree_structure(
                    tuple(0 for _ in outs)),
            )
            for i, o in enumerate(outs):
                o._grad_node = node
                o._out_index = i
                o._stop_gradient = False
        return out if single else outs


# -- functional transforms ---------------------------------------------------


def _fn_on_arrays(func):
    def f(*arrays):
        res = func(*[Tensor(a) for a in arrays])
        return unwrap(res)
    return f


def vjp(func, xs, v=None):
    """reference: python/paddle/autograd/functional.py:22"""
    xs_t = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [unwrap(x) for x in xs_t]
    out, pullback = jax.vjp(_fn_on_arrays(func), *arrays)
    if v is None:
        v_arr = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_arr = unwrap(v)
    grads = pullback(v_arr)
    wrap = lambda tree: jax.tree_util.tree_map(Tensor, tree)
    grads_w = [Tensor(g) for g in grads]
    return wrap(out), grads_w if len(grads_w) > 1 else grads_w[0]


def jvp(func, xs, v=None):
    """reference: python/paddle/autograd/functional.py:79"""
    xs_t = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [unwrap(x) for x in xs_t]
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrays]
    else:
        v_t = v if isinstance(v, (list, tuple)) else [v]
        tangents = [unwrap(t) for t in v_t]
    out, tangent_out = jax.jvp(_fn_on_arrays(func), tuple(arrays),
                               tuple(tangents))
    wrap = lambda tree: jax.tree_util.tree_map(Tensor, tree)
    return wrap(out), wrap(tangent_out)


class Jacobian:
    """reference: python/paddle/autograd/functional.py:165 — lazy full
    jacobian; here computed via jax.jacrev on first access."""

    def __init__(self, func, xs, is_batched=False):
        self._xs = xs if isinstance(xs, (list, tuple)) else [xs]
        arrays = [unwrap(x) for x in self._xs]
        jac_fn = jax.jacrev(_fn_on_arrays(func),
                            argnums=tuple(range(len(arrays))))
        self._jac = jac_fn(*arrays)
        self._is_batched = is_batched

    def __getitem__(self, idx):
        j = self._jac
        if isinstance(j, tuple) and len(j) == 1:
            j = j[0]
        arr = j
        if isinstance(arr, tuple):
            arr = jnp.concatenate(
                [a.reshape(a.shape[0], -1) for a in arr], axis=-1)
        else:
            arr = arr.reshape(arr.shape[0], -1) if arr.ndim > 2 else arr
        return Tensor(arr[idx] if idx is not None else arr)

    def numpy(self):
        return self[slice(None)].numpy()


class Hessian:
    def __init__(self, func, xs, is_batched=False):
        self._xs = xs if isinstance(xs, (list, tuple)) else [xs]
        arrays = [unwrap(x) for x in self._xs]
        hess_fn = jax.hessian(_fn_on_arrays(func),
                              argnums=tuple(range(len(arrays))))
        self._hess = hess_fn(*arrays)

    def __getitem__(self, idx):
        h = self._hess
        while isinstance(h, tuple) and len(h) == 1:
            h = h[0]
        if isinstance(h, tuple):
            raise NotImplementedError("multi-input Hessian indexing")
        n = 1
        for s in h.shape[:h.ndim // 2]:
            n *= s
        arr = h.reshape(n, n)
        return Tensor(arr[idx] if idx is not None else arr)


# -- prim-mode shims (folded in from the deprecated incubate.autograd) ------
# The reference lowers ops to autodiff primitives ("prim mode") to do what
# jax.vjp/jvp do natively; on TPU every trace already IS the primitive
# graph, so these are honest no-ops kept for API parity.

def enable_prim():
    """No-op: jax traces ARE the primitive graph."""


def disable_prim():
    """No-op (see enable_prim)."""


def prim_enabled() -> bool:
    return True


__all__ += ["enable_prim", "disable_prim", "prim_enabled"]
