"""Alias package: the parallelism stack lives in paddle_tpu.distributed
(mesh, collectives, mp_layers, pipeline, sharding, fleet).  This namespace
re-exports it under the build plan's `parallel/` name."""
from ..distributed import *  # noqa: F401,F403
from ..distributed import collective, fleet, mesh, mp_layers, pipeline, sharding  # noqa: F401
from ..distributed.mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                                     RowParallelLinear, TensorParallel,
                                     VocabParallelEmbedding,
                                     get_rng_state_tracker,
                                     with_sharding_constraint)
from ..distributed.pipeline import (LayerDesc, PipelineLayer, PipelineParallel,
                                    SegmentLayers, SharedLayerDesc,
                                    spmd_pipeline)
