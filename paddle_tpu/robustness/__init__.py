"""paddle_tpu.robustness — chaos-hardened training infrastructure.

Four pieces, documented in ROBUSTNESS.md:

* :mod:`.faultpoints` — deterministic fault injection: named sites
  compiled into the production checkpoint/store/launch/jit/amp paths
  (no-op when disabled), driven by a seeded :class:`FaultPlan` under
  ``chaos(plan)`` so every recovery path is unit-testable.
* :mod:`.retry` — jittered exponential backoff with deadline and a typed
  :class:`RetryError`; the shared policy behind store client ops and
  checkpoint IO.
* :mod:`.preemption` — :class:`PreemptionGuard` (SIGTERM/SIGUSR1 →
  boundary-checked flag) and :data:`PREEMPTED_RC`, the restart-eligible
  exit code the elastic launcher recognizes.
* :mod:`.sentinel` — :class:`DivergenceSentinel`: NaN/Inf + loss-spike
  detection over a bounded ring of host-side snapshots, with bit-identical
  rollback.

Everything here is stdlib-only at import time (jax is touched lazily), so
any layer of the stack can depend on it without cycles.
"""
from . import faultpoints  # noqa: F401
from . import retry  # noqa: F401
from . import preemption  # noqa: F401
from . import sentinel  # noqa: F401
from .faultpoints import FaultPlan, chaos, declare, faultpoint  # noqa: F401
from .preemption import PREEMPTED_RC, PreemptionGuard  # noqa: F401
from .retry import RetryError, retry_call, retrying  # noqa: F401
from .sentinel import DivergenceError, DivergenceSentinel  # noqa: F401

__all__ = [
    "faultpoints", "retry", "preemption", "sentinel",
    "FaultPlan", "chaos", "declare", "faultpoint",
    "PREEMPTED_RC", "PreemptionGuard",
    "RetryError", "retry_call", "retrying",
    "DivergenceError", "DivergenceSentinel",
]
