"""Jittered exponential backoff with deadline — the one retry policy every
transient-failure path in the stack shares (TCPStore client ops, checkpoint
shard visibility/reads, launcher respawns).

Design constraints:

* **Typed terminal error** — a retry budget that runs dry raises
  :class:`RetryError` carrying the attempt count, elapsed time and the last
  underlying exception, never a bare re-raise that hides how long and how
  often recovery was attempted.
* **Transient-only by default** — :func:`transient` matches connection
  resets/timeouts and a short list of retryable errnos; ``ENOSPC``/
  ``EACCES``/``ENOENT`` style errors fail FAST (retrying a full disk ten
  times just delays the loud failure the operator needs to see).
* **Deterministic under test** — ``sleep`` and ``rng`` are injectable, so
  the chaos suite asserts the exact backoff sequence without real waiting.

Env knobs (read per call, documented in ROBUSTNESS.md):
``PADDLE_TPU_RETRY_TRIES`` (default 5), ``PADDLE_TPU_RETRY_BASE_DELAY``
(default 0.05 s), ``PADDLE_TPU_RETRY_MAX_DELAY`` (default 2 s).
"""
from __future__ import annotations

import errno as _errno
import functools
import os
import random
import time
from typing import Callable, Iterator, Optional

__all__ = ["RetryError", "retry_call", "retrying", "transient",
           "backoff_delays", "env_float"]

#: OSError errnos worth retrying (transient IO / network hiccups).  ENOSPC,
#: EACCES, ENOENT etc. are deliberately absent: not transient.
_TRANSIENT_ERRNOS = frozenset({
    _errno.EAGAIN, _errno.EBUSY, _errno.EINTR, _errno.EIO, _errno.ESTALE,
    _errno.ETIMEDOUT, _errno.ECONNRESET, _errno.ECONNREFUSED,
    _errno.ECONNABORTED, _errno.EPIPE, _errno.ENETRESET,
    _errno.EHOSTUNREACH, _errno.ENETUNREACH, _errno.ENETDOWN,
})


class RetryError(RuntimeError):
    """All attempts exhausted (count or deadline).  ``last_error`` holds the
    final underlying exception (also chained as ``__cause__``)."""

    def __init__(self, name: str, attempts: int, elapsed: float,
                 last_error: BaseException):
        super().__init__(
            "%s failed after %d attempt(s) over %.2fs; last error: %r"
            % (name, attempts, elapsed, last_error))
        self.name = name
        self.attempts = attempts
        self.elapsed = elapsed
        self.last_error = last_error


def transient(exc: BaseException) -> bool:
    """Default retry predicate: connection-level and short-lived OS errors."""
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError)):
        return True
    if isinstance(exc, OSError):
        return exc.errno in _TRANSIENT_ERRNOS
    return False


def backoff_delays(base: float = 0.05, factor: float = 2.0,
                   cap: float = 2.0, jitter: float = 0.0,
                   rng: Optional[random.Random] = None) -> Iterator[float]:
    """Infinite ``base * factor**k`` (capped) delay stream; with ``jitter``
    in (0, 1], each delay is scaled by ``1 ± jitter`` uniformly so a pod of
    hosts retrying the same dead store does not re-stampede it in sync."""
    delay = float(base)
    while True:
        d = min(delay, cap)
        if jitter:
            r = rng.random() if rng is not None else random.random()
            d *= 1.0 + jitter * (2.0 * r - 1.0)
        yield max(0.0, d)
        delay = min(delay * factor, cap)


def env_float(name: str, default: float) -> float:
    """Read a float knob from the environment (shared by the retry policy
    and the store's timeout knobs); unset/empty -> ``default``."""
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError("%s must be a number of seconds, got %r"
                         % (name, raw))


_env_float = env_float  # internal alias


def retry_call(fn: Callable, *args,
               retry_on=transient,
               tries: Optional[int] = None,
               base_delay: Optional[float] = None,
               max_delay: Optional[float] = None,
               deadline: Optional[float] = None,
               jitter: float = 0.25,
               rng: Optional[random.Random] = None,
               sleep: Callable[[float], None] = time.sleep,
               on_retry: Optional[Callable] = None,
               name: Optional[str] = None,
               **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying matching failures with
    jittered exponential backoff.

    ``retry_on`` is a predicate (exception -> bool) or an exception
    class/tuple; non-matching exceptions propagate immediately, untouched.
    ``deadline`` (seconds, wall clock from the first attempt) bounds total
    time regardless of ``tries``.  ``on_retry(exc, attempt, delay)`` runs
    before each sleep — the hook where a store client reconnects its dead
    socket.  Exhaustion raises :class:`RetryError` from the last error.
    """
    if tries is None:
        tries = int(_env_float("PADDLE_TPU_RETRY_TRIES", 5))
    if base_delay is None:
        base_delay = _env_float("PADDLE_TPU_RETRY_BASE_DELAY", 0.05)
    if max_delay is None:
        max_delay = _env_float("PADDLE_TPU_RETRY_MAX_DELAY", 2.0)
    if isinstance(retry_on, (tuple, list)) or isinstance(retry_on, type):
        excs = tuple(retry_on) if isinstance(retry_on, (tuple, list)) \
            else (retry_on,)
        matcher = lambda e: isinstance(e, excs)
    else:
        matcher = retry_on
    # the label feeds the robustness.retry_attempts{op=} metric, whose
    # value space must stay bounded (catalog contract): never repr(fn) —
    # that embeds a memory address, minting a fresh series per callable
    # object.  functools.partial unwraps one level to the target's name.
    target = getattr(fn, "func", fn)
    label = (name or getattr(fn, "__qualname__", None)
             or getattr(target, "__qualname__", None) or type(fn).__name__)
    delays = backoff_delays(base_delay, cap=max_delay, jitter=jitter, rng=rng)
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            if not matcher(e):
                raise
            elapsed = time.monotonic() - start
            out_of_budget = attempt >= tries or (
                deadline is not None and elapsed >= deadline)
            if out_of_budget:
                raise RetryError(label, attempt, elapsed, e) from e
            delay = next(delays)
            if deadline is not None:
                delay = min(delay, max(0.0, deadline - elapsed))
            from ..observability import registry as _metrics
            _metrics.counter("robustness.retry_attempts",
                             ("op",)).labels(op=label).inc()
            if on_retry is not None:
                on_retry(e, attempt, delay)
            sleep(delay)


def retrying(**cfg):
    """Decorator form of :func:`retry_call` with a fixed policy."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, *args, name=getattr(fn, "__qualname__",
                                                      None), **cfg, **kwargs)
        return wrapper
    return deco
