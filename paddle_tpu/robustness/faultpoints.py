"""Deterministic fault injection (the chaos tier of the robustness stack).

Production modules mark their failure-prone operations with *faultpoints* —
named sites like ``faultpoint("checkpoint.shard_write", path=...)`` placed
immediately before (or after) the real IO/compute they shadow.  With no
:class:`FaultPlan` active the call is one module-attribute load and a
``None`` check — cheap enough to leave compiled into the production paths
permanently (asserted by tests/test_chaos.py).

Under ``chaos(plan)`` a seeded :class:`FaultPlan` fires scheduled
:class:`FaultAction`\\ s at exact site-hit indices, so every recovery path
(retry, fallback restore, emergency checkpoint, divergence rewind) is
unit-testable with *deterministic* failures: the same plan against the same
code fires the same faults at the same operations, every run.

Faults raise REAL exception types (``OSError(ENOSPC)``,
``ConnectionResetError``) tagged ``(injected)`` — the hardened code must
handle them exactly as it would the genuine article — or mutate the
context the site handed in (torn shard file, bit-flip, NaN batch), which
the instrumented code reads back.

Sites are declared with :func:`declare` at import time of the instrumented
module; :data:`SITES` is the live injection-site registry (documented in
ROBUSTNESS.md, asserted against in the chaos suite so the registry and the
instrumentation cannot drift apart).
"""
from __future__ import annotations

import contextlib
import errno as _errno
import os
import random
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "FaultPlan", "FaultAction", "chaos", "faultpoint", "declare",
    "active_plan", "SITES",
    "Raise", "DiskFull", "TornFile", "BitFlip", "SocketReset", "NaNBatch",
    "ForceFoundInf", "Preempt", "HardExit", "Hang",
    "CrashScopeExit", "crash_scope",
]

#: name -> one-line description of what failure the site simulates.
SITES: Dict[str, str] = {}

#: the installed plan; read unlocked on the (hot) disabled path.
_ACTIVE: Optional["FaultPlan"] = None


def declare(name: str, doc: str = "") -> str:
    """Register an injection site (idempotent).  Called at import time by
    the instrumented module so the registry mirrors the instrumentation."""
    SITES[name] = doc or SITES.get(name, "")
    return name


def faultpoint(name: str, **ctx) -> Optional[Dict[str, Any]]:
    """The per-site hook.  Disabled: one global read + None check.  Enabled:
    routes through the active plan, which may raise an injected fault or
    mutate ``ctx``; the (possibly mutated) ctx is returned so instrumented
    code can read back in-place corruptions (e.g. a poisoned batch)."""
    plan = _ACTIVE
    if plan is None:
        return None
    return plan._hit(name, ctx)


def active_plan() -> Optional["FaultPlan"]:
    return _ACTIVE


@contextlib.contextmanager
def chaos(plan: "FaultPlan"):
    """Install ``plan`` as the process-wide fault plan for the scope.

    Module-global (not thread-local) on purpose: faults must also fire on
    background threads the production code owns (the checkpoint writer
    thread), which a thread-local plan would never reach."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("nested chaos() scopes are not supported")
    _ACTIVE = plan
    try:
        yield plan
    finally:
        _ACTIVE = None


# --------------------------------------------------------------------------
# actions
# --------------------------------------------------------------------------

class FaultAction:
    """One injected failure.  ``fire`` either raises or mutates ``ctx``."""

    def fire(self, ctx: Dict[str, Any], plan: "FaultPlan"):  # pragma: no cover
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class Raise(FaultAction):
    """Raise ``exc`` (an instance, or a zero-arg factory/type)."""

    def __init__(self, exc):
        self._exc = exc

    def fire(self, ctx, plan):
        exc = self._exc() if callable(self._exc) else self._exc
        raise exc


class DiskFull(Raise):
    """ENOSPC at the site — the classic torn-NFS-quota checkpoint killer."""

    def __init__(self):
        super().__init__(lambda: OSError(
            _errno.ENOSPC, "No space left on device (injected)"))


class SocketReset(Raise):
    """Transient peer reset — what a flaky rendezvous store throws."""

    def __init__(self):
        super().__init__(
            lambda: ConnectionResetError(
                _errno.ECONNRESET, "Connection reset by peer (injected)"))


class TornFile(FaultAction):
    """Truncate ``ctx['path']`` to ``frac`` of its size: a write that the
    OS acknowledged but never fully reached the disk/NFS server."""

    def __init__(self, frac: float = 0.5):
        self.frac = float(frac)

    def fire(self, ctx, plan):
        path = ctx["path"]
        size = os.path.getsize(path)
        os.truncate(path, max(0, int(size * self.frac)))


class BitFlip(FaultAction):
    """Flip one bit of ``ctx['path']`` at a plan-seeded offset (bit rot /
    partial page flush).  Deterministic given the plan seed."""

    def fire(self, ctx, plan):
        path = ctx["path"]
        size = os.path.getsize(path)
        if size == 0:
            return
        off = plan.rng.randrange(size)
        with open(path, "r+b") as f:
            f.seek(off)
            byte = f.read(1)
            f.seek(off)
            f.write(bytes([byte[0] ^ (1 << plan.rng.randrange(8))]))


class NaNBatch(FaultAction):
    """Poison the first float leaf of ``ctx['batch']`` with NaN — the
    upstream producer of "NaN grads at step k" (a NaN input NaN-poisons the
    loss and every gradient behind it)."""

    @staticmethod
    def _is_float(b) -> bool:
        dt = getattr(b, "dtype", None)
        if dt is None:
            return False
        # numpy kinds: 'f' float, 'V' covers ml_dtypes bfloat16
        return getattr(dt, "kind", None) in ("f", "V") \
            or str(dt).startswith(("float", "bfloat"))

    def fire(self, ctx, plan):
        batch = ctx["batch"]
        out, poisoned = [], False
        for b in batch:
            if not poisoned and self._is_float(b):
                out.append(b * float("nan"))
                poisoned = True
            else:
                out.append(b)
        ctx["batch"] = tuple(out) if isinstance(batch, tuple) else out


class ForceFoundInf(FaultAction):
    """Flip the GradScaler's found-inf verdict to True: a simulated fp16
    overflow without needing overflow-scale gradients."""

    def fire(self, ctx, plan):
        ctx["found_inf"] = True


class Preempt(FaultAction):
    """Simulated SIGTERM: flips every live PreemptionGuard's flag exactly
    as the real signal handler would (no actual signal delivery, so it is
    safe inside pytest workers and background threads)."""

    def fire(self, ctx, plan):
        from . import preemption
        preemption.simulate()


class CrashScopeExit(BaseException):
    """A :class:`HardExit` that fired inside a :func:`crash_scope`.

    ``BaseException`` on purpose: the scope models a *process death*, so
    no ``except Exception`` recovery handler between the faultpoint and
    the scope boundary may swallow it — only the harness that opened the
    scope (the router's replica thread, a test worker) catches it and
    dies the way the real process would."""

    def __init__(self, rc: int = 137):
        super().__init__("simulated process crash (rc=%d)" % rc)
        self.rc = rc


_CRASH_SCOPE = threading.local()


@contextlib.contextmanager
def crash_scope():
    """Contain :class:`HardExit` to the current thread.

    In-process fault drills that model one *process* per thread (the
    serving router runs one scheduler+engine replica per thread) need a
    replica crash to kill the replica, not the test runner: inside this
    scope a fired ``HardExit`` raises :class:`CrashScopeExit` instead of
    calling ``os._exit``.  Subprocess chaos scripts keep the real thing
    by simply not opening a scope.  Thread-local and re-entrant."""
    prev = getattr(_CRASH_SCOPE, "active", False)
    _CRASH_SCOPE.active = True
    try:
        yield
    finally:
        _CRASH_SCOPE.active = prev


class HardExit(FaultAction):
    """``os._exit(rc)`` — a crash with no cleanup, for subprocess chaos
    scripts that die mid-write.  Inside a :func:`crash_scope` the same
    injection degrades to raising :class:`CrashScopeExit` so an
    in-process replica thread can die like the process it stands in
    for without taking the host process down."""

    def __init__(self, rc: int = 137):
        self.rc = rc

    def fire(self, ctx, plan):
        if getattr(_CRASH_SCOPE, "active", False):
            raise CrashScopeExit(self.rc)
        os._exit(self.rc)


class Hang(FaultAction):
    """Sleep ``seconds`` at the site, then let the operation proceed —
    the injected *stall* (a wedged NFS write, a stuck collective, a
    deadlocked peer) rather than an injected crash.  Nothing raises and
    nothing is corrupted: the only signal is the missing progress,
    which is exactly what the liveness watchdog
    (:mod:`paddle_tpu.observability.liveness`) exists to detect.
    Composes with every plan schedule like any other action."""

    def __init__(self, seconds: float = 1.0):
        self.seconds = float(seconds)

    def fire(self, ctx, plan):
        import time
        time.sleep(self.seconds)

    def __repr__(self):
        return "Hang(%gs)" % self.seconds


# --------------------------------------------------------------------------
# plan
# --------------------------------------------------------------------------

class _Rule:
    __slots__ = ("site", "action", "at", "every", "first_n", "prob",
                 "times", "fired_count")

    def __init__(self, site, action, at, every, first_n, prob, times):
        self.site = site
        self.action = action
        self.at = at
        self.every = every
        self.first_n = first_n
        self.prob = prob
        self.times = times
        self.fired_count = 0

    def should_fire(self, index: int, rng: random.Random) -> bool:
        if self.times is not None and self.fired_count >= self.times:
            return False
        if self.at is not None:
            return index == self.at
        if self.every is not None:
            return index % self.every == 0
        if self.first_n is not None:
            return index < self.first_n
        if self.prob is not None:
            return rng.random() < self.prob
        return index == 0  # default: fire on the first hit only

    def describe(self):
        sched = ("at=%r" % self.at if self.at is not None else
                 "every=%r" % self.every if self.every is not None else
                 "first_n=%r" % self.first_n if self.first_n is not None else
                 "prob=%r" % self.prob if self.prob is not None else "at=0")
        return "%s[%s -> %r]" % (self.site, sched, self.action)


class FaultPlan:
    """A seeded, scheduled set of fault rules.

    ``inject(site, action, at=k)`` fires ``action`` on the site's k-th hit
    (0-based, counted per plan); ``every=n`` / ``first_n=n`` / ``prob=p``
    (plan-RNG, so seeded-deterministic) / ``times=m`` (cap total firings)
    compose the schedule.  ``plan.fired`` logs every firing as
    ``(site, hit_index, action_name)`` for post-hoc assertions, and
    ``assert_all_fired()`` fails a test whose scheduled faults never ran
    (a chaos test that silently injected nothing proves nothing).
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self._rules: List[_Rule] = []
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.fired: List[Tuple[str, int, str]] = []

    def inject(self, site: str, action: FaultAction, *, at: Optional[int] = None,
               every: Optional[int] = None, first_n: Optional[int] = None,
               prob: Optional[float] = None,
               times: Optional[int] = None) -> "FaultPlan":
        if site not in SITES:
            raise ValueError(
                "unknown faultpoint site %r — declared sites: %s (declare() "
                "test-local sites before injecting into them)"
                % (site, sorted(SITES)))
        if isinstance(action, type):
            action = action()
        if not isinstance(action, FaultAction):
            raise TypeError("action must be a FaultAction, got %r"
                            % (type(action).__name__,))
        self._rules.append(_Rule(site, action, at, every, first_n, prob,
                                 times))
        return self

    # -- runtime -----------------------------------------------------------
    def _hit(self, site: str, ctx: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            index = self._counts.get(site, 0)
            self._counts[site] = index + 1
            due = [r for r in self._rules
                   if r.site == site and r.should_fire(index, self.rng)]
            for r in due:
                r.fired_count += 1
                self.fired.append((site, index, repr(r.action)))
        # fire OUTSIDE the lock: an action may block, exit, or re-enter
        # another faultpoint via the recovery path it triggers
        if due:
            from ..observability import flight as _flight
            from ..observability import registry as _metrics
            _metrics.counter("robustness.faultpoint_fires",
                             ("site",)).labels(site=site).inc(len(due))
            for r in due:
                _flight.record("faultpoint", site=site, index=index,
                               action=repr(r.action))
        for r in due:
            try:
                r.action.fire(ctx, self)
            except BaseException as e:
                # a faultpoint-raised crash is a flight-dump trigger: the
                # ring already holds the firing event recorded above
                _flight.crash_dump({
                    "kind": "faultpoint", "site": site, "index": index,
                    "action": repr(r.action), "error": repr(e)})
                raise
        return ctx

    # -- assertions --------------------------------------------------------
    def hits(self, site: str) -> int:
        """How many times the site was reached (fired or not)."""
        return self._counts.get(site, 0)

    def fired_at(self, site: str) -> List[int]:
        return [i for s, i, _a in self.fired if s == site]

    def assert_all_fired(self):
        unfired = [r.describe() for r in self._rules if r.fired_count == 0]
        if unfired:
            raise AssertionError(
                "scheduled faults never fired (instrumented site not "
                "reached?): %s" % ", ".join(unfired))
