"""Divergence detection + rollback: catch NaN/Inf and loss spikes, rewind
the training state to the last good host-side snapshot instead of letting
a poisoned update walk the run off a cliff.

A diverged step is *worse* than a crashed one: the optimizer state is
already contaminated when the loss curve shows it, and periodic
checkpoints happily persist the contamination.  The sentinel keeps a
bounded ring of host-RAM snapshots (``_to_host`` copies of
``TrainStep.state_dict()`` + GradScaler + LR-scheduler + global RNG
state, so a rewound run replays bit-identically) taken only after steps
whose loss passed inspection, and on a trip restores the newest one —
falling back to older snapshots on repeated trips until the ring runs
dry, which raises a typed :class:`DivergenceError`.

Composition with the fp16 skip path: when a ``GradScaler`` (or the
pipeline trainer's ``_grads_finite`` gate) already *skipped* the update
that produced a non-finite loss, the parameters were never touched — the
sentinel counts those but only rewinds after ``scaler_grace`` consecutive
skipped-and-bad steps, letting dynamic loss scaling do its job first.
"""
from __future__ import annotations

import math
import warnings
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

__all__ = ["DivergenceError", "DivergenceWarning", "DivergenceSentinel"]


class DivergenceError(RuntimeError):
    """Loss diverged and no usable snapshot remains to rewind to."""


class DivergenceWarning(UserWarning):
    """Emitted (loudly) on every rewind, naming the step rewound to."""


class DivergenceSentinel:
    """Watch the loss stream of a ``jit.TrainStep``-style trainer; rewind
    on divergence.

    ``train_step`` needs only ``state_dict()``/``set_state_dict()`` (the
    incubate.checkpoint contract, which ``jit.TrainStep`` implements).

    Trip conditions, checked by :meth:`observe`:

    * non-finite loss (NaN/Inf), or
    * ``loss > spike_factor * median(recent window)`` once at least
      ``min_history`` finite losses are recorded.

    ``observe(step, loss)`` returns ``None`` for a healthy step, or the
    snapshot step that was restored — the caller re-runs from the batch
    AFTER that step (data order and RNG state rewind with the snapshot, so
    the replayed trajectory is bit-identical to a never-diverged run).
    """

    def __init__(self, train_step, scaler=None, *, window: int = 32,
                 spike_factor: float = 10.0, min_history: int = 5,
                 snapshot_every: int = 10, max_snapshots: int = 3,
                 scaler_grace: int = 3):
        if max_snapshots < 1:
            raise ValueError("max_snapshots must be >= 1")
        self.train_step = train_step
        self.scaler = scaler
        self.window = int(window)
        self.spike_factor = float(spike_factor)
        self.min_history = int(min_history)
        self.snapshot_every = int(snapshot_every)
        self.scaler_grace = int(scaler_grace)
        self._losses: Deque[Tuple[int, float]] = deque(maxlen=self.window)
        self._ring: Deque[Dict[str, Any]] = deque(maxlen=int(max_snapshots))
        self._skip_streak = 0
        self.rewinds: List[Tuple[int, int, float]] = []  # (bad_step, to, loss)

    # -- snapshots ----------------------------------------------------------
    def snapshot(self, step: int):
        """Host-side copy of everything a bit-identical replay needs.
        ``_to_host`` (the checkpoint fetch) copies device arrays into host
        RAM, so later donated/overwritten device buffers cannot corrupt the
        ring retroactively."""
        from ..core import get_rng_state
        from ..incubate.checkpoint import _to_host

        snap = {"step": int(step),
                "train": _to_host(self.train_step.state_dict()),
                "rng": get_rng_state()}
        if self.scaler is not None and hasattr(self.scaler, "state_dict"):
            snap["scaler"] = dict(self.scaler.state_dict())
        self._ring.append(snap)

    @property
    def snapshots_available(self) -> int:
        return len(self._ring)

    # -- observation --------------------------------------------------------
    def _baseline(self) -> Optional[float]:
        if len(self._losses) < self.min_history:
            return None
        vals = sorted(v for _s, v in self._losses)
        mid = len(vals) // 2
        return vals[mid] if len(vals) % 2 else 0.5 * (vals[mid - 1]
                                                      + vals[mid])

    def _is_bad(self, loss: float) -> bool:
        if not math.isfinite(loss):
            return True
        base = self._baseline()
        return base is not None and abs(loss) > self.spike_factor * \
            max(abs(base), 1e-12)

    def observe(self, step: int, loss) -> Optional[int]:
        """Inspect ``loss`` for step ``step``.  Healthy: record it,
        snapshot on schedule, return ``None``.  Diverged: rewind and return
        the restored snapshot's step."""
        lv = float(loss)
        if self._is_bad(lv):
            skipped = self.scaler is not None and getattr(
                self.scaler, "last_step_skipped", False)
            if skipped:
                # the fp16 gate already refused this update — params are
                # intact; give loss scaling `scaler_grace` steps to adapt
                self._skip_streak += 1
                if self._skip_streak < self.scaler_grace:
                    return None
            return self.rewind(bad_step=step, bad_loss=lv)
        self._skip_streak = 0
        self._losses.append((int(step), lv))
        if self.snapshot_every > 0 and step % self.snapshot_every == 0:
            self.snapshot(step)
        return None

    # -- rollback -----------------------------------------------------------
    def rewind(self, bad_step: Optional[int] = None,
               bad_loss: float = float("nan")) -> int:
        """Restore the newest snapshot (consuming it — a re-trip falls back
        to the next-older one).  Returns the restored snapshot's step."""
        from ..core import set_rng_state

        if not self._ring:
            from ..observability import flight as _flight
            _flight.crash_dump({
                "kind": "divergence", "step": bad_step,
                "loss": repr(bad_loss), "rewinds": len(self.rewinds)})
            raise DivergenceError(
                "loss diverged at step %s (loss=%r) and the snapshot ring "
                "is exhausted — no known-good state to rewind to; restore "
                "from the last on-disk checkpoint instead"
                % (bad_step, bad_loss))
        snap = self._ring.pop()
        self.train_step.set_state_dict(snap["train"])
        set_rng_state(snap["rng"])
        if self.scaler is not None and "scaler" in snap and hasattr(
                self.scaler, "load_state_dict"):
            self.scaler.load_state_dict(dict(snap["scaler"]))
            if hasattr(self.scaler, "_last_skipped"):
                self.scaler._last_skipped = False
        # drop loss history recorded after the restored step: it belongs
        # to the abandoned timeline and would skew the spike baseline
        while self._losses and self._losses[-1][0] > snap["step"]:
            self._losses.pop()
        self._skip_streak = 0
        self.rewinds.append((int(bad_step) if bad_step is not None else -1,
                             snap["step"], bad_loss))
        from ..observability import flight as _flight
        from ..observability import registry as _metrics
        _metrics.counter("train.divergence_rollbacks").inc()
        _flight.record("divergence_rollback", bad_step=bad_step,
                       to_step=snap["step"], loss=repr(bad_loss))
        warnings.warn(
            "divergence at step %s (loss=%r): rewound training state to "
            "step %d (%d snapshot(s) left)"
            % (bad_step, bad_loss, snap["step"], len(self._ring)),
            DivergenceWarning, stacklevel=3)
        return snap["step"]
