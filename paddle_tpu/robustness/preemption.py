"""Preemption-safe shutdown: catch the platform's eviction signal, finish
the step, drain one emergency checkpoint, exit with a *distinct* rc.

TPU pods (and spot/preemptible VMs generally) deliver SIGTERM with a short
grace window before the hard kill.  The default behavior — interpreter
death mid-step — loses everything since the last periodic checkpoint and
is indistinguishable, at the launcher, from a crash.  The guard turns the
signal into a cooperative flag checked at step/epoch boundaries
(``TrainEpochRange`` does this automatically), and :data:`PREEMPTED_RC`
lets the supervisor tell "evicted, restart me" from "crashed, back off":
the elastic launcher restarts a preempted worker without consuming its
crash-restart budget.

Env: ``PADDLE_TPU_PREEMPTION_SIGNAL`` — comma-separated signal names or
numbers to treat as preemption notice (default ``SIGTERM``; add
``SIGUSR1`` for schedulers that use a softer pre-notice).
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import weakref
from typing import List, Optional

__all__ = ["PREEMPTED_RC", "PreemptionGuard", "simulate"]

#: Exit code of a worker that drained its emergency checkpoint and left on
#: preemption notice.  75 = BSD EX_TEMPFAIL ("temporary failure, retry"):
#: restart-eligible, never counted as a crash.
PREEMPTED_RC = 75

#: every constructed guard, so chaos `Preempt` can flip them without a
#: real signal (signal delivery is unsafe under pytest / non-main threads)
_guards: "weakref.WeakSet[PreemptionGuard]" = weakref.WeakSet()


def _signals_from_env() -> List[signal.Signals]:
    spec = os.environ.get("PADDLE_TPU_PREEMPTION_SIGNAL", "SIGTERM")
    out = []
    for tok in (t.strip() for t in spec.split(",")):
        if not tok:
            continue
        if tok.isdigit():
            out.append(signal.Signals(int(tok)))
        elif hasattr(signal, tok):
            out.append(getattr(signal, tok))
        else:
            raise ValueError(
                "PADDLE_TPU_PREEMPTION_SIGNAL: unknown signal %r" % tok)
    if not out:
        raise ValueError("PADDLE_TPU_PREEMPTION_SIGNAL is set but empty")
    return out


class PreemptionGuard:
    """Flag-flipping signal handler for cooperative preemption handling.

    ``install=True`` (default) registers the handler immediately — only
    valid on the main thread, as CPython requires.  ``install=False``
    builds a passive guard whose flag is flipped by :func:`simulate` (the
    chaos path) or :meth:`set` — useful in tests and worker threads.

    The previous handler for each signal is saved and restored by
    :meth:`uninstall` (also run on context-manager exit); it is NOT
    chained at signal time — the whole point is to *replace* the default
    die-now behavior with a boundary-checked flag.
    """

    def __init__(self, signals=None, install: bool = True):
        self._flag = threading.Event()
        self._pending_flight: Optional[str] = None  # deferred dump source
        self._pending_lock = threading.Lock()       # exactly-once claim
        self.signals = list(signals) if signals is not None \
            else _signals_from_env()
        self._old = {}
        self._installed = False
        _guards.add(self)
        if install:
            self.install()

    # -- handler lifecycle --------------------------------------------------
    def install(self) -> "PreemptionGuard":
        if not self._installed:
            for sig in self.signals:
                self._old[sig] = signal.signal(sig, self._on_signal)
            self._installed = True
        return self

    def uninstall(self):
        if self._installed:
            for sig, old in self._old.items():
                try:
                    signal.signal(sig, old)
                except (ValueError, OSError):  # non-main thread / torn down
                    pass
            self._old.clear()
            self._installed = False

    def _on_signal(self, signum, frame):
        # signal-handler frame: flip the flag and DEFER the flight dump.
        # The handler interrupts the main thread mid-bytecode — it may
        # be inside the flight ring's / a metric's non-reentrant lock,
        # and a synchronous dump here could deadlock (or do heavy IO at
        # the worst moment).  The dump fires at the first `preempted`
        # poll, which is exactly the drain boundary this guard exists
        # to reach.
        self._flag.set()
        self._pending_flight = "signal:%s" % signal.Signals(signum).name
        sys.stderr.write(
            "[preemption] received %s — draining at the next step/epoch "
            "boundary (rc=%d)\n"
            % (signal.Signals(signum).name, PREEMPTED_RC))
        sys.stderr.flush()

    def _fire(self, source: str):
        """Flip the flag; the FIRST fire per armed window also triggers a
        flight-recorder dump (the black box's 'we are being evicted'
        snapshot — no-op unless the recorder is armed).  Only called
        from normal (non-signal) frames: `set()`/chaos `simulate()`, or
        the deferred-signal path in :meth:`preempted`."""
        first = not self._flag.is_set()
        self._flag.set()
        if first:
            self._dump_flight(source)

    def _dump_flight(self, source: str):
        from ..observability import flight as _flight
        _flight.record("preemption", source=source)
        _flight.crash_dump({"kind": "preemption", "source": source})

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- flag ---------------------------------------------------------------
    @property
    def preempted(self) -> bool:
        p = self._flag.is_set()
        if p and self._pending_flight is not None:
            # first safe-context poll after a real signal: emit the
            # deferred flight dump here (normal frame, no interrupted
            # locks beneath us).  The claim is locked so two concurrent
            # pollers produce exactly one dump.
            with self._pending_lock:
                src, self._pending_flight = self._pending_flight, None
            if src is not None:
                self._dump_flight(src)
        return p

    def set(self):
        """Flip the flag programmatically (chaos / external schedulers)."""
        self._fire("set")

    def clear(self):
        self._flag.clear()
        self._pending_flight = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._flag.wait(timeout)


def simulate() -> int:
    """Flip every live guard's flag, as the real signal handler would.
    Returns how many guards were flipped.  This is what the chaos
    ``Preempt`` action calls — deterministic, thread-safe, no kernel
    signal delivery involved."""
    flipped = 0
    for g in list(_guards):
        g.set()
        flipped += 1
    return flipped
