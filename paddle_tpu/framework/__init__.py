"""Framework-level utilities: save/load, dtype defaults, RNG
(reference: python/paddle/framework/)."""
from __future__ import annotations

import io as _io
import os
import pickle

import numpy as np

from ..core import seed, get_rng_state, set_rng_state  # noqa: F401
from ..core.dtype import get_default_dtype, set_default_dtype  # noqa: F401
from ..core.tensor import Parameter, Tensor


class _TensorPayload:
    """Pickle-stable tensor container (arrays as numpy + dtype tag)."""

    def __init__(self, t: Tensor):
        self.array = np.asarray(t._array)
        self.is_parameter = isinstance(t, Parameter)
        self.name = t.name
        self.stop_gradient = t.stop_gradient


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(obj)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj):
    if isinstance(obj, _TensorPayload):
        if obj.is_parameter:
            t = Parameter(obj.array, name=obj.name)
        else:
            t = Tensor(obj.array)
            t.name = obj.name
        t.stop_gradient = obj.stop_gradient
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """paddle.save equivalent (reference: python/paddle/framework/io.py:568).

    Accepts nested state_dicts of Tensors; path may be a file path or a
    writable file-like object.
    """
    payload = _pack(obj)
    if hasattr(path, "write"):
        pickle.dump(payload, path, protocol=protocol)
        return
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(payload, f, protocol=protocol)


def load(path, **configs):
    """paddle.load equivalent (reference: python/paddle/framework/io.py:784)."""
    if hasattr(path, "read"):
        return _unpack(pickle.load(path))
    with open(path, "rb") as f:
        return _unpack(pickle.load(f))


def save_to_memory(obj):
    buf = _io.BytesIO()
    save(obj, buf)
    buf.seek(0)
    return buf


class CPUPlace:
    def __repr__(self):
        return "CPUPlace"


class TPUPlace:
    def __init__(self, idx=0):
        self.idx = idx

    def __repr__(self):
        return f"TPUPlace({self.idx})"


# API-compat aliases: "CUDAPlace" = the accelerator place
CUDAPlace = TPUPlace
XPUPlace = TPUPlace


def in_dynamic_mode():
    return True


in_dygraph_mode = in_dynamic_mode
