"""paddle.fft — discrete Fourier transforms (reference surface:
python/paddle/fft.py, backed by phi fft kernels
paddle/phi/kernels/funcs/fft.h).

TPU-native: jnp.fft lowers to XLA's FFT HLO.  Norm conventions follow the
reference ("backward" default, "forward", "ortho").
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import wrap_op

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    if norm is None:
        return "backward"
    if norm not in ("backward", "forward", "ortho"):
        raise ValueError(f"Unexpected norm: {norm!r} (expected 'forward', "
                         "'backward' or 'ortho')")
    return norm


def _mk1(jfn, name):
    def op(x, n=None, axis=-1, norm="backward", **kw):
        return jfn(x, n=n, axis=axis, norm=_norm(norm))
    op.__name__ = name
    return wrap_op(op, name=name)


def _mk2(jfn, name):
    def op(x, s=None, axes=(-2, -1), norm="backward", **kw):
        return jfn(x, s=s, axes=tuple(axes), norm=_norm(norm))
    op.__name__ = name
    return wrap_op(op, name=name)


def _mkn(jfn, name):
    def op(x, s=None, axes=None, norm="backward", **kw):
        return jfn(x, s=s, axes=axes, norm=_norm(norm))
    op.__name__ = name
    return wrap_op(op, name=name)


fft = _mk1(jnp.fft.fft, "fft")
ifft = _mk1(jnp.fft.ifft, "ifft")
rfft = _mk1(jnp.fft.rfft, "rfft")
irfft = _mk1(jnp.fft.irfft, "irfft")
hfft = _mk1(jnp.fft.hfft, "hfft")
ihfft = _mk1(jnp.fft.ihfft, "ihfft")

fft2 = _mk2(jnp.fft.fft2, "fft2")
ifft2 = _mk2(jnp.fft.ifft2, "ifft2")
rfft2 = _mk2(jnp.fft.rfft2, "rfft2")
irfft2 = _mk2(jnp.fft.irfft2, "irfft2")

fftn = _mkn(jnp.fft.fftn, "fftn")
ifftn = _mkn(jnp.fft.ifftn, "ifftn")
rfftn = _mkn(jnp.fft.rfftn, "rfftn")
irfftn = _mkn(jnp.fft.irfftn, "irfftn")


def _hfft_nd(x, s, axes, norm, default_all_axes):
    # hfftN = fftN over the leading axes, then hfft over the last
    # (verified against scipy.fft.hfft2 — an ifftN leading stage is NOT
    # the correct decomposition)
    if axes is None:
        axes = tuple(range(x.ndim)) if default_all_axes else (-2, -1)
    axes = tuple(axes)
    lead = jnp.fft.fftn(x, s=None if s is None else tuple(s)[:-1],
                        axes=axes[:-1], norm=_norm(norm))
    return jnp.fft.hfft(lead, n=None if s is None else tuple(s)[-1],
                        axis=axes[-1], norm=_norm(norm))


def _ihfft_nd(x, s, axes, norm, default_all_axes):
    if axes is None:
        axes = tuple(range(x.ndim)) if default_all_axes else (-2, -1)
    axes = tuple(axes)
    tail = jnp.fft.ihfft(x, n=None if s is None else tuple(s)[-1],
                         axis=axes[-1], norm=_norm(norm))
    return jnp.fft.ifftn(tail, s=None if s is None else tuple(s)[:-1],
                         axes=axes[:-1], norm=_norm(norm))


@wrap_op
def hfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return _hfft_nd(x, s, axes, norm, default_all_axes=False)


@wrap_op
def ihfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return _ihfft_nd(x, s, axes, norm, default_all_axes=False)


@wrap_op
def hfftn(x, s=None, axes=None, norm="backward"):
    return _hfft_nd(x, s, axes, norm, default_all_axes=True)


@wrap_op
def ihfftn(x, s=None, axes=None, norm="backward"):
    return _ihfft_nd(x, s, axes, norm, default_all_axes=True)


@wrap_op
def fftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.fftfreq(int(n), d=float(d))
    return out if dtype is None else out.astype(dtype)


@wrap_op
def rfftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    return out if dtype is None else out.astype(dtype)


@wrap_op
def fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


@wrap_op
def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)
